"""CLIP vision tower — the image half of CLIP, on the shared encoder path.

Reference coverage: ``deepspeed/module_inject/containers/clip.py``
(HFCLIPLayerPolicy — one policy serves BOTH towers, since CLIPEncoderLayer
is shared) used by the Stable-Diffusion pipeline injection. TPU-native
re-design: the encoder layers ARE models/transformer.py layers (pre-LN,
quick_gelu, learned positions via the standard table); only the front-end
is vision-specific — a patch-embedding conv, a class token, and HF's
``pre_layrnorm`` (expressed as the transformer's embed_norm). The tower is
a ModelSpec, so init_inference serves it like any encoder.
"""

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.transformer import (
    ModelSpec, TransformerConfig, forward as _tf_forward,
    init_params as _tf_init, logical_axes as _tf_axes)

Params = Dict[str, Any]


def vision_transformer_config(*, image_size: int = 224,
                              patch_size: int = 32,
                              hidden_size: int = 768,
                              num_layers: int = 12, num_heads: int = 12,
                              intermediate_size: Optional[int] = None,
                              norm_eps: float = 1e-5,
                              activation: str = "quick_gelu",
                              dtype=jnp.float32) -> TransformerConfig:
    """The encoder half of the tower as a TransformerConfig: non-causal,
    pre-LN, learned positions over (patches + class token), embed_norm =
    HF's pre_layrnorm, final_norm = post_layernorm."""
    n_pos = (image_size // patch_size) ** 2 + 1
    return TransformerConfig(
        vocab_size=8,   # no token lookup — inputs_embeds path only
        hidden_size=hidden_size, num_layers=num_layers,
        num_heads=num_heads,
        intermediate_size=intermediate_size or 4 * hidden_size,
        max_seq_len=n_pos, norm_eps=norm_eps,
        position_type="learned", activation=activation,
        norm_type="layernorm", causal=False, qkv_bias=True,
        # post_layernorm applies only to the POOLED class token in HF's
        # vision tower — last_hidden_state is pre-norm
        embed_norm=True, final_norm=False, tie_embeddings=True,
        dtype=dtype, attention_impl="xla")


@dataclasses.dataclass(frozen=True)
class CLIPVisionSpec:
    image_size: int = 224
    patch_size: int = 32
    tcfg: TransformerConfig = None


def clip_vision_encode(params: Params, pixel_values,
                       spec: CLIPVisionSpec):
    """pixel_values [B, H, W, 3] (NHWC) -> hidden states
    [B, 1 + patches, hidden] (fp32). The class token is row 0 (HF's
    pooled path takes post_layernorm of it)."""
    cfg = spec.tcfg
    x = jnp.asarray(pixel_values).astype(cfg.dtype)
    if x.shape[1] != spec.image_size or x.shape[2] != spec.image_size:
        # fail fast: an off-size image would silently CLAMP the learned
        # position gather (JAX out-of-bounds gathers clamp, not raise)
        raise ValueError(f"pixel_values {x.shape[1]}x{x.shape[2]} != "
                         f"spec.image_size {spec.image_size}")
    patches = jax.lax.conv_general_dilated(
        x, params["patch_embed"].astype(cfg.dtype),
        (spec.patch_size, spec.patch_size), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    B = patches.shape[0]
    tok = patches.reshape(B, -1, cfg.hidden_size)
    cls = jnp.broadcast_to(
        params["class_embed"].astype(cfg.dtype)[None, None],
        (B, 1, cfg.hidden_size))
    embeds = jnp.concatenate([cls, tok], axis=1)
    h, _ = _tf_forward(params, None, cfg, inputs_embeds=embeds,
                       return_hidden=True)
    return h


def clip_vision_pooled(params: Params, hidden, spec: CLIPVisionSpec):
    """HF's pooled output: post_layernorm of the class token."""
    from deepspeed_tpu.models.transformer import _norm
    cfg = dataclasses.replace(spec.tcfg, norm_type="layernorm")
    return _norm(hidden[:, 0], params["post_ln_scale"],
                 params["post_ln_bias"], cfg)


def init_clip_vision_params(key, spec: CLIPVisionSpec) -> Params:
    cfg = spec.tcfg
    p = _tf_init(key, cfg)
    p.pop("tok_embed", None)
    p["post_ln_scale"] = jnp.ones((cfg.hidden_size,), jnp.float32)
    p["post_ln_bias"] = jnp.zeros((cfg.hidden_size,), jnp.float32)
    k1, k2 = jax.random.split(jax.random.fold_in(key, 31))
    fan_in = spec.patch_size * spec.patch_size * 3
    p["patch_embed"] = (jax.random.normal(
        k1, (spec.patch_size, spec.patch_size, 3, cfg.hidden_size))
        / math.sqrt(fan_in)).astype(jnp.float32)
    p["class_embed"] = (jax.random.normal(k2, (cfg.hidden_size,))
                        * 0.02).astype(jnp.float32)
    return p


def clip_vision_logical_axes(spec: CLIPVisionSpec) -> Params:
    axes = dict(_tf_axes(spec.tcfg))
    axes.pop("tok_embed", None)
    axes["patch_embed"] = (None, None, None, "embed")
    axes["class_embed"] = ("embed",)
    axes["post_ln_scale"] = ("unmodeled",)
    axes["post_ln_bias"] = ("unmodeled",)
    return axes


def make_clip_vision_model(spec: CLIPVisionSpec,
                           name: str = "clip-vision") -> ModelSpec:
    return ModelSpec(
        init=lambda key: init_clip_vision_params(key, spec),
        loss_fn=None,
        apply=lambda params, pixel_values, **kw:
            clip_vision_encode(params, pixel_values, spec),
        logical_axes=clip_vision_logical_axes(spec),
        config=spec,
        name=name,
    )


def load_clip_vision_params(src, spec: CLIPVisionSpec,
                            dtype=np.float32) -> Params:
    """Convert an HF CLIPVisionModel / full CLIPModel state dict to the
    tower's param tree. Reference analogue: HFCLIPLayerPolicy's weight
    extraction (clip.py:40-68), plus the vision-only embedding front-end.
    Small enough that a one-shot (non-streaming) conversion is fine."""
    sd = src
    if hasattr(src, "state_dict"):
        sd = {k: v.detach().cpu().numpy() for k, v in
              src.state_dict().items()}
    cfg = spec.tcfg

    def get(key):
        for pre in ("vision_model.", ""):
            if pre + key in sd:
                return np.asarray(sd[pre + key], dtype)
        raise KeyError(key)

    L = cfg.num_layers
    p: Params = {
        # torch conv OIHW -> HWIO
        "patch_embed": np.transpose(
            get("embeddings.patch_embedding.weight"), (2, 3, 1, 0)),
        "class_embed": get("embeddings.class_embedding"),
        "pos_embed": get("embeddings.position_embedding.weight"),
        "embed_norm_scale": get("pre_layrnorm.weight"),
        "embed_norm_bias": get("pre_layrnorm.bias"),
        "post_ln_scale": get("post_layernorm.weight"),
        "post_ln_bias": get("post_layernorm.bias"),
    }
    names = {
        "wq": ("self_attn.q_proj.weight", True),
        "bq": ("self_attn.q_proj.bias", False),
        "wk": ("self_attn.k_proj.weight", True),
        "bk": ("self_attn.k_proj.bias", False),
        "wv": ("self_attn.v_proj.weight", True),
        "bv": ("self_attn.v_proj.bias", False),
        "wo": ("self_attn.out_proj.weight", True),
        "bo": ("self_attn.out_proj.bias", False),
        "ln1_scale": ("layer_norm1.weight", False),
        "ln1_bias": ("layer_norm1.bias", False),
        "ln2_scale": ("layer_norm2.weight", False),
        "ln2_bias": ("layer_norm2.bias", False),
        "w_in": ("mlp.fc1.weight", True),
        "b_in": ("mlp.fc1.bias", False),
        "w_out": ("mlp.fc2.weight", True),
        "b_out": ("mlp.fc2.bias", False),
    }
    layers: Params = {}
    for ours, (theirs, transpose) in names.items():
        rows = []
        for i in range(L):
            w = get(f"encoder.layers.{i}.{theirs}")
            rows.append(w.T if transpose else w)
        layers[ours] = np.stack(rows)
    p["layers"] = layers
    return jax.tree.map(jnp.asarray, p)
