"""Diffusion UNet family — the spatial/diffusers corner, TPU-first.

Reference coverage: ``deepspeed/model_implementations/diffusers/unet.py`` /
``vae.py`` (CUDA-graphed UNet/VAE wrappers), the diffusers attention policy
(``module_inject/containers/unet.py``, ``clip.py``, ``vae.py``) and the
spatial kernels (``csrc/spatial/csrc/opt_bias_add.cu``).

TPU-native re-design: the reference's pieces dissolve into the compiler —
CUDA-graph capture is jit caching, and the fused bias-add variants are
ordinary XLA fusions (conv + bias + nonlinearity fuse without a kernel,
SURVEY §2.11 "spatial: XLA fusion, no kernel needed"). What remains real is
the MODEL: a residual UNet with timestep embeddings and bottleneck
self-attention, expressed as a ModelSpec so the training engine (any ZeRO
stage) and the inference engine accept it like any transformer.

Layout is NHWC (TPU conv layout); channels carry the "mlp" logical axis so
tensor parallelism column-shards conv output channels the same way it
shards MLP weights.
"""

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 3
    out_channels: int = 3
    base_channels: int = 64
    channel_mults: Tuple[int, ...] = (1, 2)
    num_res_blocks: int = 1
    time_embed_dim: int = 256
    attn_heads: int = 4              # bottleneck self-attention
    norm_groups: int = 8
    # cross-attention context width (e.g. the CLIP text hidden size) — the
    # SD-style conditioning path; None = unconditioned UNet
    context_dim: Optional[int] = None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def _timestep_embedding(t, dim: int):
    """Sinusoidal timestep embedding (the DDPM convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _conv(x, w, b=None, stride: int = 1):
    y = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if b is not None:
        y = y + b.astype(x.dtype)   # bias-add fuses into the conv epilogue
    return y


def _group_norm(x, scale, bias, groups: int):
    B, H, W, C = x.shape
    g = math.gcd(groups, C)
    x32 = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mean = x32.mean(axis=(1, 2, 4), keepdims=True)
    var = x32.var(axis=(1, 2, 4), keepdims=True)
    x32 = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
    x32 = x32.reshape(B, H, W, C)
    return (x32 * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _init_conv(key, kh, kw, cin, cout, dt, scale=None):
    fan_in = kh * kw * cin
    s = scale if scale is not None else 1.0 / math.sqrt(fan_in)
    return (jax.random.normal(key, (kh, kw, cin, cout)) * s).astype(dt)


def _res_block_params(key, cin, cout, temb, dt):
    """temb=None -> no timestep-conditioning entries (the VAE's blocks)."""
    ks = jax.random.split(key, 4)
    p = {
        "norm1_scale": jnp.ones((cin,), dt), "norm1_bias": jnp.zeros((cin,), dt),
        "conv1": _init_conv(ks[0], 3, 3, cin, cout, dt),
        "conv1_b": jnp.zeros((cout,), dt),
        "norm2_scale": jnp.ones((cout,), dt), "norm2_bias": jnp.zeros((cout,), dt),
        "conv2": _init_conv(ks[2], 3, 3, cout, cout, dt, scale=1e-4),
        "conv2_b": jnp.zeros((cout,), dt),
    }
    if temb:
        p["temb_w"] = (jax.random.normal(ks[1], (temb, cout))
                       / math.sqrt(temb)).astype(dt)
        p["temb_b"] = jnp.zeros((cout,), dt)
    if cin != cout:
        p["skip"] = _init_conv(ks[3], 1, 1, cin, cout, dt)
    return p


def _res_block(x, emb, p, cfg):
    """GroupNorm-silu-conv residual block; ``emb=None`` (no temb_w in p)
    serves the VAE, which has no timestep conditioning. cfg only needs
    ``norm_groups``."""
    h = _group_norm(x, p["norm1_scale"], p["norm1_bias"], cfg.norm_groups)
    h = _conv(jax.nn.silu(h), p["conv1"], p["conv1_b"])
    if emb is not None:
        h = h + (jax.nn.silu(emb) @ p["temb_w"].astype(emb.dtype)
                 + p["temb_b"].astype(emb.dtype))[:, None, None, :]
    h = _group_norm(h, p["norm2_scale"], p["norm2_bias"], cfg.norm_groups)
    h = _conv(jax.nn.silu(h), p["conv2"], p["conv2_b"])
    skip = _conv(x, p["skip"]) if "skip" in p else x
    return skip + h


def _attn_params(key, c, dt, kv_dim: Optional[int] = None):
    ks = jax.random.split(key, 4)
    kv = kv_dim or c
    s = 1.0 / math.sqrt(c)
    sk = 1.0 / math.sqrt(kv)
    return {"norm_scale": jnp.ones((c,), dt), "norm_bias": jnp.zeros((c,), dt),
            "wq": (jax.random.normal(ks[0], (c, c)) * s).astype(dt),
            "wk": (jax.random.normal(ks[1], (kv, c)) * sk).astype(dt),
            "wv": (jax.random.normal(ks[2], (kv, c)) * sk).astype(dt),
            "wo": (jax.random.normal(ks[3], (c, c)) * 1e-4).astype(dt)}


def _spatial_attention(x, p, cfg: UNetConfig, context=None):
    """Bottleneck attention over H*W tokens (the diffusers AttentionBlock;
    reference wraps it with the CLIP/UNet policy). context [B, T, ctx_dim]
    switches K/V to the conditioning tokens (SD cross-attention)."""
    B, H, W, C = x.shape
    h = _group_norm(x, p["norm_scale"], p["norm_bias"], cfg.norm_groups)
    tok = h.reshape(B, H * W, C)
    nh = cfg.attn_heads
    hd = C // nh
    kv_src = tok if context is None else context.astype(tok.dtype)
    q = (tok @ p["wq"].astype(tok.dtype)).reshape(B, H * W, nh, hd)
    k = (kv_src @ p["wk"].astype(tok.dtype)).reshape(B, -1, nh, hd)
    v = (kv_src @ p["wv"].astype(tok.dtype)).reshape(B, -1, nh, hd)
    s = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32)
    a = jax.nn.softmax(s / math.sqrt(hd), axis=-1).astype(tok.dtype)
    o = jnp.einsum("bnst,btnd->bsnd", a, v).reshape(B, H * W, C)
    o = o @ p["wo"].astype(o.dtype)
    return x + o.reshape(B, H, W, C)


def init_unet_params(key, cfg: UNetConfig) -> Params:
    dt = cfg.param_dtype
    ks = iter(jax.random.split(key, 64))
    ch = cfg.base_channels
    temb = cfg.time_embed_dim
    p: Params = {
        "temb_w1": (jax.random.normal(next(ks), (temb, temb))
                    / math.sqrt(temb)).astype(dt),
        "temb_b1": jnp.zeros((temb,), dt),
        "temb_w2": (jax.random.normal(next(ks), (temb, temb))
                    / math.sqrt(temb)).astype(dt),
        "temb_b2": jnp.zeros((temb,), dt),
        "conv_in": _init_conv(next(ks), 3, 3, cfg.in_channels, ch, dt),
        "conv_in_b": jnp.zeros((ch,), dt),
    }
    chans = [ch]
    c = ch
    for li, mult in enumerate(cfg.channel_mults):
        cout = ch * mult
        for bi in range(cfg.num_res_blocks):
            p[f"down_{li}_{bi}"] = _res_block_params(next(ks), c, cout,
                                                     temb, dt)
            c = cout
            chans.append(c)
        if li != len(cfg.channel_mults) - 1:
            p[f"down_{li}_pool"] = _init_conv(next(ks), 3, 3, c, c, dt)
            p[f"down_{li}_pool_b"] = jnp.zeros((c,), dt)
            chans.append(c)
    p["mid_block1"] = _res_block_params(next(ks), c, c, temb, dt)
    p["mid_attn"] = _attn_params(next(ks), c, dt)
    if cfg.context_dim:
        # SD-style conditioning: cross-attention over the text-encoder
        # tokens at the bottleneck
        p["mid_xattn"] = _attn_params(next(ks), c, dt,
                                      kv_dim=cfg.context_dim)
    p["mid_block2"] = _res_block_params(next(ks), c, c, temb, dt)
    for li, mult in reversed(list(enumerate(cfg.channel_mults))):
        cout = ch * mult
        for bi in range(cfg.num_res_blocks + 1):
            p[f"up_{li}_{bi}"] = _res_block_params(
                next(ks), c + chans.pop(), cout, temb, dt)
            c = cout
        if li != 0:
            p[f"up_{li}_conv"] = _init_conv(next(ks), 3, 3, c, c, dt)
            p[f"up_{li}_conv_b"] = jnp.zeros((c,), dt)
    p["norm_out_scale"] = jnp.ones((c,), dt)
    p["norm_out_bias"] = jnp.zeros((c,), dt)
    p["conv_out"] = _init_conv(next(ks), 3, 3, c, cfg.out_channels, dt,
                               scale=1e-4)
    p["conv_out_b"] = jnp.zeros((cfg.out_channels,), dt)
    return p


def unet_forward(params: Params, x, t, cfg: UNetConfig, context=None):
    """x: [B, H, W, in_channels]; t: [B] diffusion timestep; context:
    optional [B, T, context_dim] conditioning tokens (CLIP text hidden
    states) -> eps prediction [B, H, W, out_channels]."""
    x = x.astype(cfg.dtype)
    emb = _timestep_embedding(t, cfg.time_embed_dim).astype(cfg.dtype)
    emb = jax.nn.silu(emb @ params["temb_w1"].astype(cfg.dtype)
                      + params["temb_b1"].astype(cfg.dtype))
    emb = emb @ params["temb_w2"].astype(cfg.dtype) \
        + params["temb_b2"].astype(cfg.dtype)

    h = _conv(x, params["conv_in"], params["conv_in_b"])
    skips = [h]
    for li, mult in enumerate(cfg.channel_mults):
        for bi in range(cfg.num_res_blocks):
            h = _res_block(h, emb, params[f"down_{li}_{bi}"], cfg)
            skips.append(h)
        if li != len(cfg.channel_mults) - 1:
            h = _conv(h, params[f"down_{li}_pool"],
                      params[f"down_{li}_pool_b"], stride=2)
            skips.append(h)
    if (context is None) != ("mid_xattn" not in params):
        raise ValueError(
            "conditioned UNet mismatch: context_dim models REQUIRE a "
            "context (pass null-text embeddings for the unconditional "
            "branch, the SD convention); unconditioned models accept "
            "none")
    h = _res_block(h, emb, params["mid_block1"], cfg)
    h = _spatial_attention(h, params["mid_attn"], cfg)
    if context is not None:
        h = _spatial_attention(h, params["mid_xattn"], cfg,
                               context=context)
    h = _res_block(h, emb, params["mid_block2"], cfg)
    for li, mult in reversed(list(enumerate(cfg.channel_mults))):
        for bi in range(cfg.num_res_blocks + 1):
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = _res_block(h, emb, params[f"up_{li}_{bi}"], cfg)
        if li != 0:
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = _conv(h, params[f"up_{li}_conv"], params[f"up_{li}_conv_b"])
    h = _group_norm(h, params["norm_out_scale"], params["norm_out_bias"],
                    cfg.norm_groups)
    out = _conv(jax.nn.silu(h), params["conv_out"], params["conv_out_b"])
    return out.astype(jnp.float32)


def unet_logical_axes(cfg: UNetConfig) -> Params:
    """Conv kernels column-shard their OUTPUT channels over the tensor axis
    (the "mlp" rule) — the AutoTP analogue for spatial models."""
    shapes = jax.eval_shape(lambda k: init_unet_params(k, cfg),
                            jax.random.PRNGKey(0))

    def one(path, leaf):
        name = str(getattr(path[-1], "key", path[-1]))
        if leaf.ndim == 4:   # conv HWIO: shard output channels
            return (None, None, None, "mlp")
        if leaf.ndim == 2:   # dense [in, out]
            return ("embed", "mlp")
        return ("unmodeled",)

    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    return jax.tree_util.tree_unflatten(
        treedef, [one(p, l) for p, l in flat])


def denoise_loss(params: Params, batch: Dict[str, Any], cfg: UNetConfig,
                 rng=None, deterministic: bool = True):
    """Standard DDPM epsilon-prediction MSE. batch: {"x": noisy input,
    "t": timesteps, "target": the noise to predict}."""
    ctx = batch.get("context")
    pred = unet_forward(params, jnp.asarray(batch["x"]),
                        jnp.asarray(batch["t"]), cfg,
                        context=jnp.asarray(ctx) if ctx is not None
                        else None)
    target = jnp.asarray(batch["target"], jnp.float32)
    return jnp.mean(jnp.square(pred - target))


def make_unet_model(cfg: UNetConfig, name: str = "unet"):
    """ModelSpec for the engines: train with any ZeRO stage, run under
    init_inference (which treats non-transformer specs as plain jitted
    forwards — no KV cache, no GEMV fusion)."""
    from deepspeed_tpu.models.transformer import ModelSpec
    return ModelSpec(
        init=lambda key: init_unet_params(key, cfg),
        loss_fn=lambda params, batch, rng=None, deterministic=True:
            denoise_loss(params, batch, cfg, rng, deterministic),
        apply=lambda params, x, t=None, context=None, **kw: unet_forward(
            params, x, t if t is not None else jnp.zeros(
                (jnp.asarray(x).shape[0],), jnp.int32), cfg,
            context=context),
        logical_axes=unet_logical_axes(cfg),
        config=cfg,
        name=name,
    )
