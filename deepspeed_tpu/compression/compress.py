"""Compression: QAT weight/activation quantization + structured pruning.

Reference: ``deepspeed/compression/compress.py:92`` (init_compression /
redundancy_clean — walks the module tree replacing Linear with
LinearLayer_Compress per the config's `different_groups`), ``basic_layer.py``
(fake-quant + pruning masks inside forward), ``config.py`` (the
shared_parameters/different_groups schema).

TPU-native re-design: no module surgery — compression is a pure pytree
transform applied to the parameters INSIDE the jitted train step:
``params' = transform(params, step)`` with straight-through gradients, so the
optimizer still updates full-precision masters while the forward sees
quantized/pruned weights (exactly the semantics the reference builds with
hooked modules). `redundancy_clean` applies the transform permanently for
export. Schedules are traced on `step`, so no recompiles as ratios kick in.
"""

import dataclasses
import fnmatch
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer import fake_quant
from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass
class _Rule:
    kind: str                 # weight_quant | sparse | row | head
    patterns: List[str]
    offset: int = 0           # schedule_offset: active from this step
    bits: int = 8
    dense_ratio: float = 1.0  # fraction of weights/rows/heads KEPT
    num_heads: Optional[int] = None


def _section_rules(kind: str, section: Dict[str, Any]) -> List[_Rule]:
    if not section:
        return []
    shared = section.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return []
    offset = int(shared.get("schedule_offset", 0))
    rules = []
    for _name, grp in (section.get("different_groups") or {}).items():
        p = grp.get("params", {})
        rules.append(_Rule(
            kind=kind,
            patterns=[str(m) for m in grp.get("modules", ["*"])],
            offset=offset,
            bits=int(p.get("target_bits", p.get("bits", 8))),
            dense_ratio=float(p.get("dense_ratio", 1.0)),
            num_heads=p.get("num_heads")))
    if not rules:  # enabled with no groups -> apply to everything
        rules.append(_Rule(kind=kind, patterns=["*"], offset=offset))
    return rules


def _match(path: str, patterns: List[str]) -> bool:
    return any(fnmatch.fnmatch(path, pat) or pat in path for pat in patterns)


class CompressionTransform:
    """Param-tree compression transform (build once, apply per step)."""

    def __init__(self, config: Dict[str, Any]):
        self.rules: List[_Rule] = []
        self.rules += _section_rules("weight_quant",
                                     config.get("weight_quantization", {}))
        self.rules += _section_rules("sparse", config.get("sparse_pruning", {}))
        self.rules += _section_rules("row", config.get("row_pruning", {}))
        self.rules += _section_rules("head", config.get("head_pruning", {}))
        for unsupported in ("activation_quantization", "channel_pruning",
                            "layer_reduction"):
            sec = config.get(unsupported, {})
            if sec.get("shared_parameters", {}).get("enabled") or \
                    sec.get("enabled"):
                raise NotImplementedError(
                    f"{unsupported} is not implemented (weight quantization "
                    "and sparse/row/head pruning are)")
        if not self.rules:
            raise ValueError("compression config has no enabled section")

    # ------------------------------------------------------------------
    def _leaf_ops(self, path: str, leaf) -> List[_Rule]:
        if not hasattr(leaf, "ndim") or leaf.ndim < 2 or leaf.size < 64:
            return []
        return [r for r in self.rules if _match(path, r.patterns)]

    def apply(self, params, step):
        """Traced transform: params' seen by the forward at `step`."""
        step = jnp.asarray(step, jnp.int32)

        def one(path_tuple, leaf):
            path = "/".join(str(getattr(k, "key", k)) for k in path_tuple)
            out = leaf
            for r in self._leaf_ops(path, leaf):
                active = step >= r.offset
                if r.kind == "weight_quant":
                    q = fake_quant(out, bits=r.bits)
                    out = jnp.where(active, q, out)
                elif r.kind == "sparse":
                    mask = _topk_mask(out, r.dense_ratio)
                    out = jnp.where(active, out * mask, out)
                elif r.kind == "row":
                    mask = _row_mask(out, r.dense_ratio)
                    out = jnp.where(active, out * mask, out)
                elif r.kind == "head":
                    mask = _head_mask(out, r.dense_ratio, r.num_heads)
                    out = jnp.where(active, out * mask, out)
            return out

        return jax.tree_util.tree_map_with_path(one, params)


def _topk_mask(w, dense_ratio: float):
    """Unstructured magnitude mask keeping the top `dense_ratio` fraction
    (reference: basic_layer.py SparsePruningModule, method=l1/topk).
    stop_gradient: the mask is not differentiated (STE)."""
    a = jnp.abs(w.astype(jnp.float32)).reshape(-1)
    thresh = jnp.quantile(a, 1.0 - dense_ratio)
    mask = (jnp.abs(w.astype(jnp.float32)) >= thresh).astype(w.dtype)
    return jax.lax.stop_gradient(mask)


def _row_mask(w, dense_ratio: float):
    """Keep the highest-L2 rows (reference: row_pruning — output-channel
    structured sparsity). Rows = leading dim of the 2D view."""
    w2 = w.reshape(w.shape[0], -1) if w.ndim == 2 else \
        w.reshape(w.shape[0] * w.shape[1], -1)
    norms = jnp.linalg.norm(w2.astype(jnp.float32), axis=1)
    thresh = jnp.quantile(norms, 1.0 - dense_ratio)
    mask = (norms >= thresh).astype(w.dtype)
    shape = (w.shape[0], 1) if w.ndim == 2 else (w.shape[0], w.shape[1], 1)
    return jax.lax.stop_gradient(mask.reshape(shape))


def _head_mask(w, dense_ratio: float, num_heads: Optional[int]):
    """Mask whole attention heads by column-group norm (reference:
    head_pruning on the output projection). w: [.., nh*hd, H] — the head dim
    is the second-to-last axis split into num_heads groups."""
    if not num_heads:
        raise ValueError("head_pruning needs params.num_heads")
    *lead, In, Out = w.shape
    hd = In // num_heads
    g = w.reshape(*lead, num_heads, hd, Out).astype(jnp.float32)
    norms = jnp.sqrt(jnp.sum(g * g, axis=(-2, -1)))      # [..., nh]
    thresh = jnp.quantile(norms, 1.0 - dense_ratio, axis=-1, keepdims=True)
    mask = (norms >= thresh).astype(w.dtype)             # [..., nh]
    mask = jnp.repeat(mask[..., None], hd, axis=-1).reshape(*lead, In, 1)
    return jax.lax.stop_gradient(mask)


def init_compression(config: Dict[str, Any]) -> CompressionTransform:
    """Reference: ``compression/compress.py:92`` init_compression."""
    t = CompressionTransform(config)
    logger.info(f"compression: {len(t.rules)} rule(s) active "
                f"({', '.join(r.kind for r in t.rules)})")
    return t


def redundancy_clean(params, config: Dict[str, Any], step: int = 10 ** 9):
    """Apply the compression permanently (reference: compress.py
    redundancy_clean) — e.g. before export/save_16bit_model."""
    t = CompressionTransform(config)
    return jax.jit(lambda p: t.apply(p, step))(params)
