"""Compression: QAT weight/activation quantization + structured pruning.

Reference: ``deepspeed/compression/compress.py:92`` (init_compression /
redundancy_clean — walks the module tree replacing Linear with
LinearLayer_Compress per the config's `different_groups`), ``basic_layer.py``
(fake-quant + pruning masks inside forward), ``config.py`` (the
shared_parameters/different_groups schema).

TPU-native re-design: no module surgery — compression is a pure pytree
transform applied to the parameters INSIDE the jitted train step:
``params' = transform(params, step)`` with straight-through gradients, so the
optimizer still updates full-precision masters while the forward sees
quantized/pruned weights (exactly the semantics the reference builds with
hooked modules). `redundancy_clean` applies the transform permanently for
export. Schedules are traced on `step`, so no recompiles as ratios kick in.
"""

import dataclasses
import fnmatch
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer import fake_quant
from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass
class _Rule:
    kind: str                 # weight_quant | sparse | row | head
    patterns: List[str]
    offset: int = 0           # schedule_offset: active from this step
    bits: int = 8
    dense_ratio: float = 1.0  # fraction of weights/rows/heads KEPT
    num_heads: Optional[int] = None


def _section_rules(kind: str, section: Dict[str, Any]) -> List[_Rule]:
    if not section:
        return []
    shared = section.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return []
    offset = int(shared.get("schedule_offset", 0))
    rules = []
    for _name, grp in (section.get("different_groups") or {}).items():
        p = grp.get("params", {})
        rules.append(_Rule(
            kind=kind,
            patterns=[str(m) for m in grp.get("modules", ["*"])],
            offset=offset,
            bits=int(p.get("target_bits", p.get("bits", 8))),
            dense_ratio=float(p.get("dense_ratio", 1.0)),
            num_heads=p.get("num_heads")))
    if not rules:  # enabled with no groups -> apply to everything
        rules.append(_Rule(kind=kind, patterns=["*"], offset=offset))
    return rules


def _match(path: str, patterns: List[str]) -> bool:
    return any(fnmatch.fnmatch(path, pat) or pat in path for pat in patterns)


class CompressionTransform:
    """Param-tree compression transform (build once, apply per step)."""

    def __init__(self, config: Dict[str, Any]):
        self.rules: List[_Rule] = []
        self.rules += _section_rules("weight_quant",
                                     config.get("weight_quantization", {}))
        self.rules += _section_rules("sparse", config.get("sparse_pruning", {}))
        self.rules += _section_rules("row", config.get("row_pruning", {}))
        self.rules += _section_rules("head", config.get("head_pruning", {}))
        self.rules += _section_rules("channel",
                                     config.get("channel_pruning", {}))

        # activation quantization (reference: basic_layer.py QuantAct): not a
        # param transform — the ENGINE rebuilds the transformer with
        # activation_quant_bits once the schedule offset is reached
        self.activation_quant: Optional[Tuple[int, int]] = None  # (bits, offset)
        aq = config.get("activation_quantization", {})
        if aq.get("shared_parameters", {}).get("enabled"):
            shared = aq["shared_parameters"]
            groups = list((aq.get("different_groups") or {}).values())
            bits = int(groups[0].get("params", {}).get("bits", 8)) \
                if groups else 8
            if len(groups) > 1 or any(
                    g.get("modules", ["*"]) not in (["*"], "*")
                    for g in groups):
                logger.warning(
                    "activation_quantization applies model-wide on TPU "
                    "(post-norm activations); per-group module scoping is "
                    f"ignored — using bits={bits} from the first group")
            self.activation_quant = (bits,
                                     int(shared.get("schedule_offset", 0)))

        # layer reduction (reference: compress.py student_initialization +
        # config keep_number/teacher_layer): consumed at engine/model build
        lr_cfg = config.get("layer_reduction", {})
        self.layer_reduction: Optional[Dict[str, Any]] = None
        if lr_cfg.get("enabled"):
            self.layer_reduction = {
                "keep_number": int(lr_cfg["keep_number"]),
                "teacher_layer": list(lr_cfg.get("teacher_layer", [])),
            }

        if not self.rules and self.activation_quant is None \
                and self.layer_reduction is None:
            raise ValueError("compression config has no enabled section")

    # ------------------------------------------------------------------
    def _leaf_ops(self, path: str, leaf) -> List[_Rule]:
        if not hasattr(leaf, "ndim") or leaf.ndim < 2 or leaf.size < 64:
            return []
        return [r for r in self.rules if _match(path, r.patterns)]

    def apply(self, params, step):
        """Traced transform: params' seen by the forward at `step`."""
        step = jnp.asarray(step, jnp.int32)

        def one(path_tuple, leaf):
            path = "/".join(str(getattr(k, "key", k)) for k in path_tuple)
            out = leaf
            for r in self._leaf_ops(path, leaf):
                active = step >= r.offset
                if r.kind == "weight_quant":
                    q = fake_quant(out, bits=r.bits)
                    out = jnp.where(active, q, out)
                elif r.kind == "sparse":
                    mask = _topk_mask(out, r.dense_ratio)
                    out = jnp.where(active, out * mask, out)
                elif r.kind == "row":
                    mask = _row_mask(out, r.dense_ratio)
                    out = jnp.where(active, out * mask, out)
                elif r.kind == "head":
                    mask = _head_mask(out, r.dense_ratio, r.num_heads)
                    out = jnp.where(active, out * mask, out)
                elif r.kind == "channel":
                    mask = _channel_mask(out, r.dense_ratio)
                    out = jnp.where(active, out * mask, out)
            return out

        return jax.tree_util.tree_map_with_path(one, params)


def _topk_mask(w, dense_ratio: float):
    """Unstructured magnitude mask keeping the top `dense_ratio` fraction
    (reference: basic_layer.py SparsePruningModule, method=l1/topk).
    stop_gradient: the mask is not differentiated (STE)."""
    a = jnp.abs(w.astype(jnp.float32)).reshape(-1)
    thresh = jnp.quantile(a, 1.0 - dense_ratio)
    mask = (jnp.abs(w.astype(jnp.float32)) >= thresh).astype(w.dtype)
    return jax.lax.stop_gradient(mask)


def _row_mask(w, dense_ratio: float):
    """Keep the highest-L2 rows (reference: row_pruning — output-channel
    structured sparsity). Rows = leading dim of the 2D view."""
    w2 = w.reshape(w.shape[0], -1) if w.ndim == 2 else \
        w.reshape(w.shape[0] * w.shape[1], -1)
    norms = jnp.linalg.norm(w2.astype(jnp.float32), axis=1)
    thresh = jnp.quantile(norms, 1.0 - dense_ratio)
    mask = (norms >= thresh).astype(w.dtype)
    shape = (w.shape[0], 1) if w.ndim == 2 else (w.shape[0], w.shape[1], 1)
    return jax.lax.stop_gradient(mask.reshape(shape))


def _head_mask(w, dense_ratio: float, num_heads: Optional[int]):
    """Mask whole attention heads by column-group norm (reference:
    head_pruning on the output projection). w: [.., nh*hd, H] — the head dim
    is the second-to-last axis split into num_heads groups."""
    if not num_heads:
        raise ValueError("head_pruning needs params.num_heads")
    *lead, In, Out = w.shape
    hd = In // num_heads
    g = w.reshape(*lead, num_heads, hd, Out).astype(jnp.float32)
    norms = jnp.sqrt(jnp.sum(g * g, axis=(-2, -1)))      # [..., nh]
    thresh = jnp.quantile(norms, 1.0 - dense_ratio, axis=-1, keepdims=True)
    mask = (norms >= thresh).astype(w.dtype)             # [..., nh]
    mask = jnp.repeat(mask[..., None], hd, axis=-1).reshape(*lead, In, 1)
    return jax.lax.stop_gradient(mask)


def _channel_mask(w, dense_ratio: float):
    """Keep the highest-L2 OUTPUT channels (reference: channel_pruning on
    conv/linear output filters). Our matmul weights are [in, out] (x @ W),
    so output channels are the LAST axis — the complement of _row_mask's
    leading-axis (input-channel) pruning."""
    w2 = w.reshape(-1, w.shape[-1]).astype(jnp.float32)
    norms = jnp.linalg.norm(w2, axis=0)                  # [out]
    thresh = jnp.quantile(norms, 1.0 - dense_ratio)
    mask = (norms >= thresh).astype(w.dtype)             # [out]
    return jax.lax.stop_gradient(mask)                   # broadcasts on last


def student_params_from_teacher(teacher_params, keep_layers: List[int]):
    """Layer reduction (reference: compress.py student_initialization +
    utils recursive getattr copy): slice the teacher's stacked layer dim to
    `keep_layers`; non-layer params copy through. Works on any tree with a
    "layers" subtree whose leaves stack layers on axis 0."""
    idx = jnp.asarray(keep_layers, jnp.int32)
    out = dict(teacher_params)
    out["layers"] = jax.tree.map(lambda a: jnp.take(a, idx, axis=0),
                                 teacher_params["layers"])
    return out


def make_distillation_loss(student_cfg, teacher_params, teacher_cfg=None,
                           alpha: float = 0.5, temperature: float = 2.0
                           ) -> Callable:
    """Knowledge-distillation loss for layer-reduced students (reference:
    the kd_loss wiring DeepSpeed-Compression pairs with layer_reduction).

    loss = alpha * CE(student, labels)
         + (1 - alpha) * T^2 * KL(teacher_T || student_T)
    Teacher runs frozen (stop_gradient) inside the same jitted step.
    """
    from deepspeed_tpu.models.transformer import forward, lm_loss

    tcfg = teacher_cfg or student_cfg

    def loss_fn(params, batch, rng=None, deterministic=True):
        from deepspeed_tpu.models.transformer import cross_entropy_loss
        ids = batch["input_ids"]
        # ONE student forward serves both terms (a second forward would
        # double student FLOPs and re-materialize the [B,S,V] logits that
        # loss_chunk exists to avoid — here the KL term needs them anyway)
        s_logits = forward(params, ids, student_cfg, dropout_rng=rng,
                           deterministic=deterministic)
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, ids.dtype)],
                axis=1)
        ce = cross_entropy_loss(s_logits, labels)
        t_logits = jax.lax.stop_gradient(
            forward(teacher_params, ids, tcfg, deterministic=True))
        T = temperature
        t_prob = jax.nn.softmax(t_logits.astype(jnp.float32) / T, axis=-1)
        s_logp = jax.nn.log_softmax(s_logits.astype(jnp.float32) / T, axis=-1)
        kl = jnp.mean(jnp.sum(t_prob * (jnp.log(t_prob + 1e-9) - s_logp),
                              axis=-1))
        return alpha * ce + (1.0 - alpha) * (T * T) * kl

    return loss_fn


def init_compression(config: Dict[str, Any]) -> CompressionTransform:
    """Reference: ``compression/compress.py:92`` init_compression."""
    t = CompressionTransform(config)
    logger.info(f"compression: {len(t.rules)} rule(s) active "
                f"({', '.join(r.kind for r in t.rules)})")
    return t


def redundancy_clean(params, config: Dict[str, Any], step: int = 10 ** 9):
    """Apply the compression permanently (reference: compress.py
    redundancy_clean) — e.g. before export/save_16bit_model."""
    t = CompressionTransform(config)
    return jax.jit(lambda p: t.apply(p, step))(params)
