from deepspeed_tpu.compression.compress import (
    CompressionTransform, init_compression, redundancy_clean)
