from deepspeed_tpu.compression.compress import (
    CompressionTransform, init_compression, make_distillation_loss,
    redundancy_clean, student_params_from_teacher)
