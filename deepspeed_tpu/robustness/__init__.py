"""Fault-tolerance subsystem: deterministic fault injection, checkpoint
integrity, bounded-retry I/O, graceful preemption.

See README "Fault tolerance" for the config reference and the elastic
preemption-recovery rung (`__graft_entry__.dryrun_multichip`).
"""

from deepspeed_tpu.robustness import events
from deepspeed_tpu.robustness.faults import (BackendFault, DispatchFault,
                                             FaultInjector, FaultSchedule,
                                             active, clear, dispatch_seam,
                                             install, install_from_config,
                                             io_seam, mutate_seam,
                                             serving_round_seam)
from deepspeed_tpu.robustness.integrity import (newest_valid_tag, prune_tags,
                                                validate_tag, write_commit_marker,
                                                write_manifest)
from deepspeed_tpu.robustness.preemption import Preempted, PreemptionHandler
from deepspeed_tpu.robustness.retry import retry_io

__all__ = [
    "BackendFault", "DispatchFault", "FaultInjector", "FaultSchedule",
    "Preempted", "PreemptionHandler", "active", "clear", "dispatch_seam",
    "events", "install", "install_from_config", "io_seam", "mutate_seam",
    "newest_valid_tag", "prune_tags", "retry_io", "serving_round_seam",
    "validate_tag", "write_commit_marker", "write_manifest",
]
