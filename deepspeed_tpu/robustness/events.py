"""Structured robustness events (``ckpt_fallback``, ``fault_recovered``, …).

Reference analogue: none — the reference logs recovery prose and loses it in
stdout. Here every recovery decision (a checkpoint fallback, a retried I/O
op, a device-fault rebuild, a preemption save) becomes a structured record
that rides the PR-3 telemetry stream: ``engine._log_step`` drains the
pending queue into ``MonitorMaster.write_records`` (JSONL sink included) at
the same window boundary as every other telemetry record, so fault handling
is observable with ZERO added steady-state syncs.

The module is deliberately leaf-level (stdlib only): ``runtime/
checkpointing``, ``elasticity/elastic_agent`` and ``robustness/retry`` all
emit through it without import cycles. ``history()`` keeps a bounded copy of
everything ever emitted for tests and post-mortems, independent of whether a
monitor drained it.
"""

import threading
import time
from typing import Any, Dict, List

from deepspeed_tpu.utils.logging import logger

_LOCK = threading.Lock()
_PENDING: List[Dict[str, Any]] = []
_HISTORY: List[Dict[str, Any]] = []
_MAX_HISTORY = 4096
# pending is bounded too: a process with no drain wired (e.g. a serving
# engine without a telemetry sink) must not grow this list forever under
# a shed storm — oldest records drop, history keeps its bounded copy
_MAX_PENDING = 4096


def emit(event_type: str, **fields) -> Dict[str, Any]:
    """Record one robustness event. Returns the record (already queued)."""
    rec = {"type": event_type, "ts": time.time(), **fields}
    with _LOCK:
        _PENDING.append(rec)
        del _PENDING[:-_MAX_PENDING]
        _HISTORY.append(rec)
        del _HISTORY[:-_MAX_HISTORY]
    logger.warning(f"robustness: {event_type} "
                   + " ".join(f"{k}={v}" for k, v in fields.items()))
    return rec


def drain() -> List[Dict[str, Any]]:
    """Pop every pending event (the engine's window-boundary drain)."""
    with _LOCK:
        out, _PENDING[:] = list(_PENDING), []
    return out


def history(event_type: str = None) -> List[Dict[str, Any]]:
    """Everything emitted this process (drained or not), newest last."""
    with _LOCK:
        out = list(_HISTORY)
    if event_type is not None:
        out = [r for r in out if r["type"] == event_type]
    return out


def clear() -> None:
    """Reset both queues (test isolation)."""
    with _LOCK:
        _PENDING[:] = []
        _HISTORY[:] = []
