"""Graceful preemption: SIGTERM -> checkpoint-and-exit.

Reference: ``launcher/launch.py:103`` kills the process tree on SIGTERM;
our ``LaunchAgent`` already forwards the signal to the user process group
and waits out a grace period before SIGKILL. This module is the *user
process* half of that contract: a ``PreemptionHandler`` latches the signal
into a flag (handlers must not checkpoint from signal context — Orbax and
JAX are not reentrant), and the training driver (``DSElasticAgent.
train_batch``, or any custom loop polling ``requested``) saves a final
checkpoint at the next step boundary and raises ``Preempted``. The launch
agent's grace window (``--kill_grace_s`` / ``DSTPU_KILL_GRACE_S``) is
exactly the budget for that save.

A preempted run resumes like any other elastic resume: rebuild the engine,
``load_checkpoint(tag=None)`` — the preemption save is the newest valid
tag in the integrity chain, so nothing is replayed.
"""

import signal
from typing import Dict, Optional, Sequence

from deepspeed_tpu.robustness import events
from deepspeed_tpu.utils.logging import logger


class Preempted(RuntimeError):
    """Raised by the training driver after the preemption checkpoint is
    durable — the caller should exit cleanly (rc 0: the work is saved)."""

    def __init__(self, message: str, step: int = -1, ckpt_path: Optional[str] = None):
        super().__init__(message)
        self.step = step
        self.ckpt_path = ckpt_path


class PreemptionHandler:
    """Latches SIGTERM (and any extra signals) into a poll-able flag.

    Usage::

        handler = PreemptionHandler().install()
        agent = DSElasticAgent(..., preemption=handler)
        try:
            while ...:
                agent.train_batch(batch_fn)
        except Preempted:
            sys.exit(0)   # checkpointed; the launch agent reaps us

    ``install``/``restore`` save and put back the previous handlers, so the
    launch agent's own forwarding (parent process) is never disturbed —
    each process owns its handlers.
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,)):
        self.signals = tuple(signals)
        self.received: Optional[int] = None
        self._requested = False
        self._prev: Dict[int, object] = {}
        self._installed = False

    def _on_signal(self, signum, _frame):
        # signal context: latch the flag only — no I/O, no JAX
        self._requested = True
        self.received = signum

    def install(self) -> "PreemptionHandler":
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def restore(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    @property
    def requested(self) -> bool:
        return self._requested

    def reset(self) -> None:
        """Clear the latch (after the preemption was handled; a resumed
        in-process driver reuses the handler)."""
        self._requested = False
        self.received = None

    def acknowledge(self, step: int, ckpt_path: Optional[str] = None) -> None:
        """Record that the checkpoint-and-exit contract was honored."""
        logger.warning(f"preemption: checkpointed at step {step}; exiting")
        events.emit("preempted", step=step, signal=self.received,
                    ckpt_path=ckpt_path)

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.restore()
