"""Bounded retry-with-backoff for host I/O.

Before this module a single transient ``OSError`` from an NVMe/AIO
read/write (a gcsfuse hiccup, an EIO under memory pressure, a full disk
racing the retention pruner) was fatal AND anonymous — the traceback named
neither the file nor the offset nor how often it had worked before. Every
host-I/O call in ``runtime/swap_tensor.py``, ``runtime/infinity.py``,
``ops/aio.py`` and ``runtime/checkpointing.py`` now goes through
``retry_io``: transient faults are retried with exponential backoff, a
recovery is a structured ``fault_recovered`` event on the telemetry stream,
and the *terminal* error names the operation, file, offset and attempt
count.
"""

import errno as _errno
import time
from typing import Callable, Optional, Tuple

from deepspeed_tpu.robustness import events
from deepspeed_tpu.utils.logging import logger

# errnos worth retrying: transient media/transport errors. ENOSPC is NOT
# retried by default — a full disk rarely un-fills within the backoff
# budget, and the caller (checkpoint save, swap writeback) has a better
# fallback (skip the save, keep the previous good tag).
TRANSIENT_ERRNOS = frozenset({
    _errno.EIO, _errno.EAGAIN, _errno.EINTR, _errno.EBUSY, _errno.ETIMEDOUT,
})


def _is_transient(err: BaseException) -> bool:
    if not isinstance(err, OSError):
        return False
    # an OSError with no errno (e.g. raised by hand, or IOError("msg"))
    # is treated as transient: the native AIO binding reports failures
    # without errno and those are exactly the calls this helper guards
    return err.errno is None or err.errno in TRANSIENT_ERRNOS


def retry_io(fn: Callable, *, what: str, path: str,
             offset: Optional[int] = None, attempts: int = 4,
             backoff_s: float = 0.05, sleep: Callable[[float], None] = None,
             retriable: Tuple = (OSError,)):
    """Run ``fn()`` with up to ``attempts`` tries.

    Retries only *transient* ``OSError``s (see ``TRANSIENT_ERRNOS``);
    anything else — ENOSPC, EACCES, a ``ValueError`` — propagates
    immediately. On success after >= 1 failure a ``fault_recovered`` event
    is emitted. The terminal error is an ``OSError`` naming ``what``,
    ``path``, ``offset`` and the attempt count, chained from the last
    underlying failure.
    """
    sleep = sleep or time.sleep
    where = path if offset is None else f"{path}@{offset}"
    last = None
    for attempt in range(1, max(1, attempts) + 1):
        try:
            result = fn()
        except retriable as e:
            if not _is_transient(e):
                raise
            last = e
            if attempt >= attempts:
                break
            logger.warning(f"{what}: transient {e!r} on {where} "
                           f"(attempt {attempt}/{attempts}); retrying")
            sleep(backoff_s * (2 ** (attempt - 1)))
            continue
        if attempt > 1:
            events.emit("fault_recovered", kind="io", what=what, path=path,
                        offset=offset, attempts=attempt)
        return result
    raise OSError(
        getattr(last, "errno", None) or _errno.EIO,
        f"{what} failed after {attempts} attempts on {where}: {last}"
    ) from last
