"""Deterministic interleaving explorer (graft-race, face 2).

The host tier is genuinely concurrent — io_uring read/write pools and
rotating staging buffers in ``runtime/infinity.py``/``runtime/swap_tensor.py``,
a watchdog round thread in ``inference/serving.py``, a background telemetry
worker in ``runtime/engine.py`` — but every analysis pass so far replays it
single-threaded. This module makes thread schedules a *controlled input*:
a cooperative scheduler serializes all tasks onto one runnable-at-a-time
interleaving chosen by an explicit decision sequence, so a harness over the
REAL classes can be run under hundreds of distinct schedules, assert its
invariants on every one, and *replay* a failing schedule bit-for-bit from
its printed id.

How control is obtained
-----------------------
Tasks only switch at *preemption points*:

* explicit ``sched.point()`` calls in harness code,
* every line of code in ``trace_files`` modules (``sys.settrace``-driven,
  so real classes are explored without modification),
* the seams the components already route through when patched in
  (``SchedExecutor`` for ``ThreadPoolExecutor``, ``SchedThread`` for
  ``threading.Thread``, ``sched.clock``/``sched.sleep`` for time).

Each task runs in a real (daemon) OS thread but is gated by a semaphore:
exactly one task runs between scheduler decisions, so execution is
sequentially consistent and fully determined by the decision sequence.

Schedule ids
------------
``r<hex>``   — seeded-random: decisions drawn from ``random.Random(seed)``.
``x1.0.2``   — explicit: the recorded decision list; the canonical REPLAY
               form every failure report carries (robust to seed-derivation
               changes, and what ``replay()`` takes).

Timeouts (``Thread.join(t)``, ``Future.result(t)``, ``sched.sleep``) run on
a VIRTUAL clock: when no task is runnable the clock jumps to the earliest
deadline, so watchdog expiry is an explored *schedule*, not wall time.
"""

import contextlib
import random
import sys
import threading as _threading
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Callable, List, Optional, Sequence, Tuple

# the scheduler's own worker threads must be REAL threads even while
# threading.Thread is patched to SchedThread inside `patched()`
_RealThread = _threading.Thread
_MAX_TRACE_TAIL = 40


class InvariantViolation(AssertionError):
    """A harness invariant broke under some schedule — the race fired."""


class ScheduleDeadlock(RuntimeError):
    """No task is runnable and no deadline can advance the clock: every
    live task waits on a condition only another blocked task could
    establish (e.g. a lock cycle)."""


class _Aborted(BaseException):
    # BaseException: must not be swallowed by harness `except Exception`
    pass


class _Task:
    __slots__ = ("name", "thread", "gate", "done", "exc", "result",
                 "pred", "deadline", "atomic", "exc_retrieved")

    def __init__(self, name: str):
        self.name = name
        self.thread = None
        self.gate = _threading.Semaphore(0)
        self.done = False
        self.exc: Optional[BaseException] = None
        self.result = None
        self.pred: Optional[Callable[[], bool]] = None
        self.deadline: Optional[float] = None
        self.atomic = 0
        self.exc_retrieved = False


def _parse_schedule(sid: str) -> Tuple[Tuple[int, ...],
                                       Optional[random.Random]]:
    if sid.startswith("r"):
        return (), random.Random(int(sid[1:] or "0", 16))
    if sid.startswith("x"):
        body = sid[1:]
        forced = tuple(int(p) for p in body.split(".") if p != "")
        return forced, None
    raise ValueError(f"bad schedule id {sid!r}: want r<hexseed> or xD.D.D")


class DeterministicScheduler:
    """One interleaving: tasks spawn real threads but run strictly one at
    a time; every preemption point hands control back here and the next
    runnable task is chosen by the schedule's decision sequence."""

    def __init__(self, schedule: str = "r0", *,
                 trace_files: Sequence[str] = (),
                 max_switches: int = 200_000):
        self.schedule_id = schedule
        self._forced, self._rng = _parse_schedule(schedule)
        self._tasks: List[_Task] = []
        self._gate = _threading.Semaphore(0)      # scheduler wakeups
        self._local = _threading.local()
        self.decisions: List[int] = []            # recorded choices
        self.branches: List[int] = []             # runnable count per choice
        self._clock = 0.0
        self._switches = 0
        self._max_switches = max_switches
        self._abort = False
        self._trace_files = tuple(trace_files)
        self.trace_tail: List[str] = []           # last N (task, tag) points

    # -- schedule identity ------------------------------------------------

    @property
    def replay_id(self) -> str:
        """Explicit form of the decisions actually taken — feed back to
        ``replay()``/``DeterministicScheduler(schedule=...)`` to reproduce
        this exact interleaving."""
        return "x" + ".".join(map(str, self.decisions))

    # -- task plumbing ----------------------------------------------------

    def current(self) -> Optional[_Task]:
        return getattr(self._local, "task", None)

    def spawn(self, fn: Callable, *args, name: Optional[str] = None,
              **kwargs) -> _Task:
        task = _Task(name or f"t{len(self._tasks)}")

        def body():
            self._local.task = task
            task.gate.acquire()                 # wait to be scheduled
            if self._trace_files:
                sys.settrace(self._make_tracer())
            try:
                if not self._abort:
                    task.result = fn(*args, **kwargs)
            except _Aborted:
                pass
            except BaseException as e:          # noqa: BLE001 — relayed
                task.exc = e
            finally:
                if self._trace_files:
                    sys.settrace(None)
                task.done = True
                self._gate.release()

        task.thread = _RealThread(target=body, daemon=True,
                                  name=f"sched:{task.name}")
        self._tasks.append(task)
        task.thread.start()
        return task

    def point(self, tag: str = "") -> None:
        """A potential context switch. No-op outside scheduler tasks and
        inside ``atomic()`` sections."""
        task = self.current()
        if task is None or task.atomic:
            return
        if self._abort:
            raise _Aborted()
        if tag:
            self.trace_tail.append(f"{task.name}@{tag}")
            del self.trace_tail[:-_MAX_TRACE_TAIL]
        self._gate.release()
        task.gate.acquire()
        if self._abort:
            raise _Aborted()

    def wait_for(self, pred: Callable[[], bool],
                 deadline: Optional[float] = None,
                 tag: str = "wait") -> bool:
        """Block the current task until ``pred()`` holds or the virtual
        clock reaches ``deadline``. Returns True iff the predicate held."""
        task = self.current()
        if task is None:                        # outside the scheduler
            return bool(pred())
        while not pred():
            if deadline is not None and self._clock >= deadline:
                return False
            task.pred = pred
            task.deadline = deadline
            try:
                self.point(tag)
            finally:
                task.pred = None
                task.deadline = None
        return True

    # -- virtual time -----------------------------------------------------

    def clock(self) -> float:
        return self._clock

    def sleep(self, dt: float) -> None:
        self.wait_for(lambda: False, deadline=self._clock + dt, tag="sleep")

    @contextlib.contextmanager
    def atomic(self):
        """Suppress preemption for the current task (models a critical
        section the code under test performs without yielding)."""
        task = self.current()
        if task is None:
            yield
            return
        task.atomic += 1
        try:
            yield
        finally:
            task.atomic -= 1

    # -- the decision loop ------------------------------------------------

    def _choose(self, n: int) -> int:
        self.branches.append(n)
        i = len(self.decisions)
        if i < len(self._forced):
            c = min(self._forced[i], n - 1)
        elif self._rng is not None:
            c = self._rng.randrange(n)
        else:
            c = 0
        self.decisions.append(c)
        return c

    def _runnable(self, t: _Task) -> bool:
        if t.pred is None:
            return True
        if t.deadline is not None and self._clock >= t.deadline:
            return True
        return bool(t.pred())

    def run(self) -> None:
        """Drive all spawned tasks to completion under this schedule; the
        first task exception (not already retrieved via a future)
        propagates."""
        try:
            while True:
                live = [t for t in self._tasks if not t.done]
                if not live:
                    break
                runnable = [t for t in live if self._runnable(t)]
                if not runnable:
                    deadlines = [t.deadline for t in live
                                 if t.deadline is not None]
                    if deadlines:
                        self._clock = min(deadlines)
                        continue
                    raise ScheduleDeadlock(
                        f"schedule {self.replay_id}: all of "
                        f"{[t.name for t in live]} blocked with no deadline "
                        f"(trace tail: {self.trace_tail[-8:]})")
                self._switches += 1
                if self._switches > self._max_switches:
                    raise ScheduleDeadlock(
                        f"schedule exceeded {self._max_switches} switches "
                        "(livelock?)")
                t = runnable[self._choose(len(runnable))]
                t.gate.release()
                self._gate.acquire()
        finally:
            self._abort_all()
        for t in self._tasks:
            if t.exc is not None and not t.exc_retrieved:
                raise t.exc

    def _abort_all(self) -> None:
        self._abort = True
        for _ in range(10_000):
            live = [t for t in self._tasks if not t.done]
            if not live:
                break
            for t in live:
                t.gate.release()
            for t in live:
                t.thread.join(0.01)

    # -- line-level preemption inside real classes ------------------------

    def _make_tracer(self):
        files = self._trace_files

        def local_trace(frame, event, arg):
            if event == "line":
                self.point(f"{frame.f_code.co_name}:{frame.f_lineno}")
            return local_trace

        def global_trace(frame, event, arg):
            if event == "call":
                fn = frame.f_code.co_filename
                if any(fn.endswith(sfx) for sfx in files):
                    return local_trace
            return None

        return global_trace

    def instrument(self, obj: Any, methods: Sequence[str]) -> Any:
        """Bracket the named bound methods of ``obj`` with preemption
        points (method-granularity interleaving over a real object)."""
        cls = type(obj).__name__
        for m in methods:
            orig = getattr(obj, m)

            def wrapped(*a, _orig=orig, _tag=f"{cls}.{m}", **k):
                self.point(_tag + ":enter")
                r = _orig(*a, **k)
                self.point(_tag + ":exit")
                return r

            setattr(obj, m, wrapped)
        return obj

    # -- patched concurrency seams ---------------------------------------

    @contextlib.contextmanager
    def patched(self, *modules, thread: bool = True):
        """Swap the concurrency seams the fleet routes through:
        ``ThreadPoolExecutor`` in each given module (they import the name
        directly) and ``threading.Thread`` globally (creations from
        non-scheduler threads fall back to real threads)."""
        sched = self
        saved = []
        for mod in modules:
            if hasattr(mod, "ThreadPoolExecutor"):
                saved.append((mod, "ThreadPoolExecutor",
                              mod.ThreadPoolExecutor))
                mod.ThreadPoolExecutor = \
                    lambda *a, **k: SchedExecutor(sched, *a, **k)
        orig_thread = _threading.Thread

        def make_thread(*a, **kw):
            if sched.current() is None and not sched._in_run_scope():
                return _RealThread(*a, **kw)
            return SchedThread(sched, *a, **kw)

        if thread:
            _threading.Thread = make_thread
        try:
            yield
        finally:
            if thread:
                _threading.Thread = orig_thread
            for mod, attr, val in saved:
                setattr(mod, attr, val)

    def _in_run_scope(self) -> bool:
        # the driving (main) thread counts as in-scope while tasks exist
        # and are not finished — harness setup code runs there too
        return any(not t.done for t in self._tasks) or not self._tasks


class SchedFuture:
    """concurrent.futures.Future protocol over a scheduler task."""

    def __init__(self, sched: DeterministicScheduler, task: _Task):
        self._sched = sched
        self._task = task

    def done(self) -> bool:
        return self._task.done

    def running(self) -> bool:
        return not self._task.done

    def cancel(self) -> bool:
        return False

    def result(self, timeout: Optional[float] = None):
        deadline = None if timeout is None \
            else self._sched.clock() + timeout
        if not self._sched.wait_for(lambda: self._task.done, deadline,
                                    tag="future.result"):
            raise FuturesTimeoutError()
        if self._task.exc is not None:
            self._task.exc_retrieved = True
            raise self._task.exc
        return self._task.result

    def exception(self, timeout: Optional[float] = None):
        deadline = None if timeout is None \
            else self._sched.clock() + timeout
        if not self._sched.wait_for(lambda: self._task.done, deadline,
                                    tag="future.exception"):
            raise FuturesTimeoutError()
        if self._task.exc is not None:
            self._task.exc_retrieved = True
        return self._task.exc


class SchedExecutor:
    """ThreadPoolExecutor stand-in: ``submit`` spawns a scheduler task.
    FIFO admission honors ``max_workers`` — a submit can't start before
    enough earlier submits finished, exactly like a real bounded pool, so
    the explorer never reports an interleaving a real 1-worker pool could
    not produce."""

    def __init__(self, sched: DeterministicScheduler,
                 max_workers: Optional[int] = None, *args, **kwargs):
        self._sched = sched
        self._max_workers = max_workers or 8
        self._tasks: List[_Task] = []
        self._n = 0

    def submit(self, fn: Callable, *args, **kwargs) -> SchedFuture:
        idx = self._n
        self._n += 1
        earlier = list(self._tasks)

        def admitted():
            self._sched.wait_for(
                lambda: sum(1 for t in earlier if not t.done)
                < self._max_workers, tag="pool.admit")
            return fn(*args, **kwargs)

        task = self._sched.spawn(admitted, name=f"pool{idx}")
        self._tasks.append(task)
        return SchedFuture(self._sched, task)

    def shutdown(self, wait: bool = True, **kwargs) -> None:
        if wait:
            self._sched.wait_for(
                lambda: all(t.done for t in self._tasks),
                tag="pool.shutdown")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(wait=True)
        return False


class SchedThread:
    """threading.Thread protocol over a scheduler task (what component
    code gets when it calls ``threading.Thread`` under ``patched()``)."""

    def __init__(self, sched: DeterministicScheduler, group=None,
                 target=None, name=None, args=(), kwargs=None, *,
                 daemon=None):
        self._sched = sched
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self._task: Optional[_Task] = None
        self.name = name or "SchedThread"
        self.daemon = bool(daemon)

    def start(self) -> None:
        if self._task is not None:
            raise RuntimeError("threads can only be started once")
        self._task = self._sched.spawn(
            self._target, *self._args, name=self.name, **self._kwargs)

    def join(self, timeout: Optional[float] = None) -> None:
        if self._task is None:
            raise RuntimeError("cannot join thread before it is started")
        deadline = None if timeout is None \
            else self._sched.clock() + timeout
        self._sched.wait_for(lambda: self._task.done, deadline,
                             tag="thread.join")

    def is_alive(self) -> bool:
        return self._task is not None and not self._task.done


class SchedLock:
    """Cooperative lock for harness code (a real ``threading.Lock`` held
    across a preemption point would deadlock the OS thread without the
    scheduler knowing; this one blocks through ``wait_for`` so the
    scheduler sees — and explores — the contention)."""

    def __init__(self, sched: DeterministicScheduler):
        self._sched = sched
        self._owner: Optional[_Task] = None

    def acquire(self) -> bool:
        self._sched.wait_for(lambda: self._owner is None, tag="lock")
        self._owner = self._sched.current() or _SENTINEL
        return True

    def release(self) -> None:
        self._owner = None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


_SENTINEL = _Task("_outside")


# -------------------------------------------------------------------------
# exploration drivers
# -------------------------------------------------------------------------

class ScheduleFailure:
    """One failing interleaving: ``replay_id`` reproduces it exactly."""

    def __init__(self, schedule_id: str, replay_id: str,
                 error: BaseException, index: int,
                 trace_tail: Sequence[str] = ()):
        self.schedule_id = schedule_id
        self.replay_id = replay_id
        self.error = error
        self.index = index
        self.trace_tail = list(trace_tail)

    def __repr__(self):
        return (f"ScheduleFailure({self.replay_id!r}, "
                f"{type(self.error).__name__}: {self.error})")


class ExploreResult:
    def __init__(self, explored: int, failures: List[ScheduleFailure]):
        self.explored = explored
        self.failures = failures

    @property
    def ok(self) -> bool:
        return not self.failures

    @property
    def first_failure(self) -> Optional[ScheduleFailure]:
        return self.failures[0] if self.failures else None


def run_schedule(harness: Callable[[DeterministicScheduler],
                                   Optional[Callable[[], None]]],
                 schedule_id: str, *, trace_files: Sequence[str] = (),
                 max_switches: int = 200_000,
                 index: int = 0) -> Optional[ScheduleFailure]:
    """Run one harness under one schedule. The harness receives the
    scheduler, spawns its tasks (and may return a final-check callable run
    after every task completed); any exception — a task's, the harness's,
    the final check's, or a deadlock — is the schedule failing."""
    sched = DeterministicScheduler(schedule_id, trace_files=trace_files,
                                   max_switches=max_switches)
    try:
        check = harness(sched)
        sched.run()
        if callable(check):
            check()
    except BaseException as e:    # noqa: BLE001 — every failure is data
        return ScheduleFailure(schedule_id, sched.replay_id, e,
                               index, sched.trace_tail)
    return None


def explore(harness, *, schedules: int = 200, seed: int = 0,
            mode: str = "random", trace_files: Sequence[str] = (),
            stop_on_failure: bool = False,
            max_switches: int = 200_000) -> ExploreResult:
    """Explore up to ``schedules`` interleavings of ``harness``.

    ``mode="random"``: schedule i runs under seed ``seed + i`` — same
    (seed, i) is always the same interleaving. ``mode="exhaustive"``:
    DFS over the decision tree (complete when the tree is smaller than
    the budget)."""
    failures: List[ScheduleFailure] = []
    explored = 0
    if mode == "random":
        for i in range(schedules):
            sid = f"r{(seed + i) & 0xffffffffffff:x}"
            fail = run_schedule(harness, sid, trace_files=trace_files,
                                max_switches=max_switches, index=i)
            explored += 1
            if fail is not None:
                failures.append(fail)
                if stop_on_failure:
                    break
        return ExploreResult(explored, failures)
    if mode != "exhaustive":
        raise ValueError(f"mode={mode!r}: want 'random' or 'exhaustive'")
    frontier: List[Tuple[int, ...]] = [()]
    seen = set()
    while frontier and explored < schedules:
        prefix = frontier.pop()
        if prefix in seen:
            continue
        seen.add(prefix)
        sid = "x" + ".".join(map(str, prefix))
        sched = DeterministicScheduler(sid, trace_files=trace_files,
                                       max_switches=max_switches)
        fail = None
        try:
            check = harness(sched)
            sched.run()
            if callable(check):
                check()
        except BaseException as e:  # noqa: BLE001
            fail = ScheduleFailure(sid, sched.replay_id, e, explored,
                                   sched.trace_tail)
        explored += 1
        if fail is not None:
            failures.append(fail)
            if stop_on_failure:
                break
        # branch: at every position past the forced prefix with >1
        # runnable, the untaken choices are new prefixes to explore
        for j in range(len(prefix), len(sched.decisions)):
            taken, width = sched.decisions[j], sched.branches[j]
            for c in range(width):
                if c != taken:
                    frontier.append(tuple(sched.decisions[:j]) + (c,))
    return ExploreResult(explored, failures)


def replay(harness, schedule_id: str, *,
           trace_files: Sequence[str] = ()) -> Optional[ScheduleFailure]:
    """Re-run one recorded schedule (the ``x...`` replay id a failure
    printed, or an ``r<seed>`` id). Returns the failure, or None if the
    schedule now passes."""
    return run_schedule(harness, schedule_id, trace_files=trace_files)
