"""Deterministic, seedable fault injection at the runtime's existing seams.

Reference analogue: none — the reference's elasticity (DSElasticAgent,
``elasticity/elastic_agent.py:25``) is only ever exercised by real cluster
failures. Here a ``FaultSchedule`` (config section ``robustness.faults``)
drives a ``FaultInjector`` that fires *exactly reproducible* faults at the
seams the production code already exposes:

  step seam       — ``DSElasticAgent.train_batch`` calls ``step(n)`` before
                    dispatching global step n; a ``device_fault`` raises
                    there (a chip loss surfaces as a failed step) and arms
                    the health-probe cull below
  probe seam      — ``DSElasticAgent._healthy_devices`` passes the probed
                    device list through ``cull``; an armed device fault
                    hides ``survivors``.. devices for the next ``probes``
                    consults (1 = a transient blip the rebuild out-waits,
                    big = a permanent shrink)
  I/O seams       — ``io_seam(category, path, offset)`` inside
                    checkpointing / swap_tensor / infinity / aio raises
                    scheduled ``OSError``s (EIO, ENOSPC, …); transient ones
                    are absorbed by ``retry_io``, terminal ones exercise the
                    caller's degradation path
  commit seam     — a ``torn_save`` raises at the ``ckpt_commit`` seam:
                    payload durable, COMMITTED never written — exactly the
                    crash-between-write-and-commit shape
  corrupt seam    — ``corrupt_payload`` truncates a manifest-listed file
                    after the manifest is written (bitrot: committed but
                    checksum-invalid)
  preemption      — delivers a real SIGTERM to this process at step n,
                    exercising the ``PreemptionHandler`` path end-to-end
                    (training: ``step`` key; serving: ``round`` key — the
                    ServingEngine drains through the same handler)
  clock           — ``make_clock(base)`` wraps the rendezvous' injectable
                    clock with scheduled skew (a skewed host reads its peers
                    as dead / itself as live: heartbeat loss without
                    touching the store)

Serving seams (ISSUE 10 — the serving tier's reliability layer calls these
at its scheduling-round boundaries; ``at``/``round`` count the seam's own
0-based INVOCATION index, exactly like the I/O seams count ops — recovery
retries re-invoke the seams, so an index is "rounds attempted", not
"rounds committed", and a fault that triggers a recovery shifts every
later index by one attempt):

  decode_dispatch — ``dispatch_seam()`` inside the watchdog-guarded quantum
                    dispatch: mode "fail" (default) raises DispatchFault (a
                    failed dispatch); mode "hang" sleeps ``hang_s`` so the
                    engine's dispatch watchdog times the round out — both
                    recover by rebuilding the batch from host-side cursors
  pool_exhaust    — ``serving_round_seam()`` returns a squeeze: the engine
                    hides (free - keep) blocks from the allocator for the
                    round, forcing a REAL exhaustion storm through the
                    scheduler's queue/preempt paths
  backend_fault   — ``serving_round_seam()`` raises BackendFault (a Pallas
                    kernel failure): the engine degrades to the XLA gather
                    backend mid-serve and logs ``backend_degraded``

Router seams (ISSUE 11 — the multi-replica ``ServingRouter`` consults
``router_seam()`` once per routing round; ``at`` counts 0-based router
rounds, independent of the per-engine ``serving_round`` counter):

  replica_kill    — SIGTERM-equivalent on one replica: its engine drains
                    through the PR-10 integrity chain, its heartbeats stop,
                    and the router must detect the loss and resume the
                    drained requests on survivors (in-flight migration)
  heartbeat_loss  — the replica stays alive and reachable but its
                    heartbeats are suppressed for ``times`` rounds: the
                    router's breaker must OPEN (``replica_degraded``) and,
                    with no drain snapshot and no death evidence, must NOT
                    migrate (fencing: never double-serve live work) —
                    recovery closes via the half-open probe
  router_partition — the replica is alive but unreachable from the router
                    for ``times`` rounds (dispatches raise); the first
                    partitioned round also writes a TORN newest generation
                    manifest into the rendezvous store, so the registry's
                    generation reads during the partition exercise the
                    ``FileRendezvous.current_generation`` fallback

Handoff seam (ISSUE 19 — the router consults ``kv_handoff_seam(payload)``
once per disaggregated KV handoff, AFTER export and BEFORE the decode
replica imports; ``at`` counts 0-based handoff attempts):

  kv_handoff      — mode "fail" (default) raises HandoffFault: the bytes
                    never arrive, the record still does — the router falls
                    back to the ordinary re-prefill migration; mode
                    "corrupt" flips bytes in the payload in place (a torn
                    transfer): the importer's crc32 check MUST refuse it
                    typed and fall back — a corrupted payload must never
                    decode garbage

Observability of injected faults (ISSUE 18): every kind above already
emits ``fault_injected`` plus its recovery record; the fleet-observability
layer adds two read-side event types an injected stall surfaces through —
``serving_phase_stall {phase, phase_ms, round_ms}`` when a warm engine's
round regresses >= 3x its window median with a non-fetch phase dominant
(a ``pool_exhaust`` squeeze or adapter-paging storm reads as
``housekeeping``-bound here), and ``trace_export {path, events,
replicas}`` when a merged Chrome trace is written. Neither is a fault
kind — they are how a fault LOOKS from the doctor's side of the glass.

Schedules are deterministic by construction: explicit entries fire at exact
step/op indices, and the optional ``seed`` only feeds probabilistic rates
through a private ``numpy`` Generator — same seed, same faults, every run.
"""

import errno as _errno
import os
import signal
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.robustness import events
from deepspeed_tpu.utils.logging import logger

_ERRNO_BY_NAME = {"EIO": _errno.EIO, "ENOSPC": _errno.ENOSPC,
                  "EAGAIN": _errno.EAGAIN, "EBUSY": _errno.EBUSY,
                  "ETIMEDOUT": _errno.ETIMEDOUT}

KINDS = ("device_fault", "step_fault", "io_error", "torn_save",
         "corrupt_payload", "preempt", "clock_skew",
         "decode_dispatch", "pool_exhaust", "backend_fault",
         "replica_kill", "heartbeat_loss", "router_partition",
         "kv_handoff")

ROUTER_KINDS = ("replica_kill", "heartbeat_loss", "router_partition")


class DispatchFault(RuntimeError):
    """Injected decode-dispatch failure (the serving engine's recovery
    path treats it exactly like a real failed dispatch)."""


class BackendFault(RuntimeError):
    """Injected decode-kernel failure: the serving engine degrades to the
    XLA gather backend and retries the round."""


class HandoffFault(RuntimeError):
    """Injected KV-handoff transfer failure (mode "fail"): the payload is
    lost in flight — the router hands the request off WITHOUT it and the
    decode replica re-prefills."""


class FaultSchedule:
    """Normalized list of fault entries + a seeded RNG for rate-based ones.

    Entry keys (dicts, from config ``robustness.faults.entries``):
      kind            one of KINDS (required)
      step            1-based global optimizer step (step/device faults,
                      preempt)
      op              I/O seam category the fault targets (io_error;
                      default matches any category)
      at              0-based operation index within that category
                      (io_error / torn_save / corrupt_payload; torn and
                      corrupt count ``ckpt_commit`` seam hits, i.e. saves)
      times           consecutive operations affected (io_error; default 1 —
                      with retry attempts > times the fault is transient)
      errno           symbolic ("EIO", "ENOSPC", …) or int (default EIO)
      survivors       device count the armed cull reports (device_fault)
      probes          health consults the cull stays armed for
                      (device_fault; default 1 = transient blip)
      skew_s / after  clock_skew: add skew_s seconds after `after` reads
      round           preempt only: 0-based serving round-seam invocation
                      (the serving alternative to `step`; recovery retries
                      advance it — see "Serving seams" above)
      mode / hang_s   decode_dispatch: "fail" (default, raises) or "hang"
                      (sleeps hang_s, default 30 — the engine's dispatch
                      watchdog must time it out); kv_handoff: "fail"
                      (default, raises HandoffFault — payload lost in
                      flight) or "corrupt" (flips payload bytes in place —
                      the importer's crc32 must refuse it typed); for
                      kv_handoff `at` counts 0-based handoff attempts
      keep            pool_exhaust: free blocks left visible during the
                      storm (default 0 = total exhaustion)
      replica         router kinds only (required): 0-based registration
                      index of the target replica; `at` counts router
                      rounds, `times` holds a heartbeat_loss /
                      router_partition condition for that many rounds
                      (replica_kill fires once — death is permanent)
      rate            instead of step/at: per-opportunity probability drawn
                      from the schedule seed (still deterministic)
    """

    def __init__(self, entries: Sequence[Dict[str, Any]] = (), seed: int = 0):
        self.seed = int(seed)
        self.entries: List[Dict[str, Any]] = []
        for i, raw in enumerate(entries):
            e = dict(raw)
            kind = e.get("kind")
            if kind not in KINDS:
                raise ValueError(f"faults.entries[{i}]: unknown kind {kind!r}"
                                 f" (choose from {KINDS})")
            # an entry with no trigger would validate and then never fire —
            # a chaos schedule that silently tests nothing
            if kind in ("device_fault", "step_fault") and "step" not in e:
                raise ValueError(f"faults.entries[{i}] ({kind}): needs "
                                 "'step' (1-based global step)")
            if kind == "preempt" and "step" not in e and "round" not in e:
                raise ValueError(f"faults.entries[{i}] ({kind}): needs "
                                 "'step' (1-based global step) or 'round' "
                                 "(0-based serving round-seam invocation)")
            if kind in ("io_error", "torn_save", "corrupt_payload",
                        "decode_dispatch", "pool_exhaust", "backend_fault",
                        "kv_handoff") \
                    + ROUTER_KINDS \
                    and "at" not in e and "rate" not in e:
                raise ValueError(f"faults.entries[{i}] ({kind}): needs 'at' "
                                 "(0-based op index) or 'rate'")
            if kind in ROUTER_KINDS:
                # the router applies these to a specific replica; an entry
                # without one would silently always hit replica 0 — make
                # the target explicit so chaos schedules read unambiguously
                if "replica" not in e:
                    raise ValueError(f"faults.entries[{i}] ({kind}): needs "
                                     "'replica' (0-based registration "
                                     "index)")
            err = e.get("errno", "EIO")
            e["errno"] = _ERRNO_BY_NAME.get(err, err) if isinstance(err, str) \
                else int(err)
            e.setdefault("times", 1)
            self.entries.append(e)

    @classmethod
    def from_config(cls, cfg) -> "FaultSchedule":
        """cfg: a FaultsConfig (config section ``robustness.faults``)."""
        return cls(entries=cfg.entries, seed=cfg.seed)


class FaultInjector:
    """Executes a FaultSchedule against the instrumented seams. Counters and
    the fired-fault log make every run's fault sequence auditable."""

    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule
        self.counters: Dict[str, int] = {}
        self.fired: List[Dict[str, Any]] = []
        self._armed_culls: List[Dict[str, Any]] = []
        self._rng = np.random.default_rng(schedule.seed)

    # -- bookkeeping ---------------------------------------------------
    def _fire(self, entry: Dict[str, Any], seam: str, **ctx):
        rec = {"kind": entry["kind"], "seam": seam, **ctx}
        self.fired.append(rec)
        events.emit("fault_injected", **rec)

    def _count(self, category: str) -> int:
        n = self.counters.get(category, 0)
        self.counters[category] = n + 1
        return n

    def _matches_index(self, e: Dict[str, Any], idx: int) -> bool:
        if "at" in e:
            return e["at"] <= idx < e["at"] + e["times"]
        rate = e.get("rate")
        return rate is not None and self._rng.random() < rate

    # -- step seam (elastic agent) -------------------------------------
    def step(self, global_step: int) -> None:
        """Called with the 1-based step about to be dispatched. Raises for
        scheduled device/step faults; delivers scheduled preemptions."""
        for e in self.schedule.entries:
            if e.get("step") != global_step or e.get("_done"):
                continue
            if e["kind"] == "preempt":
                e["_done"] = True
                self._fire(e, "step", step=global_step,
                           signal="SIGTERM")
                os.kill(os.getpid(), signal.SIGTERM)
            elif e["kind"] in ("device_fault", "step_fault"):
                e["_done"] = True
                if e["kind"] == "device_fault":
                    self._armed_culls.append({
                        "survivors": int(e.get("survivors", 0)),
                        "probes": int(e.get("probes", 1))})
                self._fire(e, "step", step=global_step)
                raise RuntimeError(
                    f"injected {e['kind']} at step {global_step} "
                    "(robustness.faults)")

    # -- probe seam (elastic agent health checks) ----------------------
    def cull(self, devices: List) -> List:
        """While a device fault is armed, hide the dead devices from the
        health probe for the configured number of consults."""
        if not self._armed_culls:
            return devices
        armed = self._armed_culls[0]
        armed["probes"] -= 1
        if armed["probes"] <= 0:
            self._armed_culls.pop(0)
        n = armed["survivors"]
        return list(devices)[:n] if n < len(devices) else list(devices)

    # -- I/O seams ------------------------------------------------------
    def op(self, category: str, path: Optional[str] = None,
           offset: Optional[int] = None) -> None:
        idx = self._count(category)
        for e in self.schedule.entries:
            if e["kind"] == "io_error" and e.get("op", category) == category \
                    and self._matches_index(e, idx):
                self._fire(e, category, path=path, offset=offset, index=idx)
                raise OSError(e["errno"],
                              f"injected io_error ({category}) "
                              "(robustness.faults)")
            if e["kind"] == "torn_save" and category == "ckpt_commit" \
                    and self._matches_index(e, idx):
                self._fire(e, category, path=path, index=idx)
                raise OSError(_errno.EIO,
                              "injected torn save: crash before commit "
                              "marker (robustness.faults)")

    def mutate_tag(self, tag_dir: str) -> None:
        """corrupt_payload seam: truncate the largest manifest-listed file
        of the `at`-th committed save (fires after the manifest, before the
        commit marker — a committed-but-bitrotten tag)."""
        idx = self._count("ckpt_mutate")
        for e in self.schedule.entries:
            if e["kind"] != "corrupt_payload" or not self._matches_index(e, idx):
                continue
            victims = []
            for root, _d, files in os.walk(tag_dir):
                for fn in files:
                    if fn in ("manifest.json", "COMMITTED"):
                        continue
                    p = os.path.join(root, fn)
                    victims.append((os.path.getsize(p), p))
            if not victims:
                continue
            _, victim = max(victims)
            keep = max(0, os.path.getsize(victim) // 2)
            with open(victim, "r+b") as f:
                f.truncate(keep)
            self._fire(e, "ckpt_mutate", path=victim, index=idx,
                       truncated_to=keep)

    # -- serving seams (ServingEngine scheduling rounds) ----------------
    def serving_round(self) -> Dict[str, Any]:
        """Round-boundary seam, called once per scheduling-round ATTEMPT
        (recovery retries included) BEFORE the admission/growth decisions.
        Delivers round-keyed preemptions (SIGTERM), raises scheduled
        BackendFaults, and returns the round's pool squeeze
        ({"squeeze": blocks-to-keep-visible or None})."""
        idx = self._count("serving_round")
        squeeze = None
        for e in self.schedule.entries:
            kind = e["kind"]
            if kind == "preempt" and e.get("round") == idx \
                    and not e.get("_done"):
                e["_done"] = True
                self._fire(e, "serving_round", round=idx, signal="SIGTERM")
                os.kill(os.getpid(), signal.SIGTERM)
            elif kind == "backend_fault" and self._matches_index(e, idx):
                self._fire(e, "serving_round", round=idx)
                raise BackendFault(
                    f"injected backend_fault at serving round {idx} "
                    "(robustness.faults)")
            elif kind == "pool_exhaust" and self._matches_index(e, idx):
                keep = int(e.get("keep", 0))
                self._fire(e, "serving_round", round=idx, keep=keep)
                squeeze = keep if squeeze is None else min(squeeze, keep)
        return {"squeeze": squeeze}

    def decode_dispatch(self) -> None:
        """Dispatch seam, called inside the engine's watchdog-guarded
        quantum dispatch. "fail" raises (failed dispatch); "hang" sleeps
        past the watchdog (hung dispatch) — the watchdog's timeout, not
        this sleep, is what the engine recovers from."""
        import time as _time
        idx = self._count("decode_dispatch")
        for e in self.schedule.entries:
            if e["kind"] != "decode_dispatch" \
                    or not self._matches_index(e, idx):
                continue
            mode = e.get("mode", "fail")
            self._fire(e, "decode_dispatch", index=idx, mode=mode)
            if mode == "hang":
                _time.sleep(float(e.get("hang_s", 30.0)))
            else:
                raise DispatchFault(
                    f"injected decode_dispatch failure (op {idx}) "
                    "(robustness.faults)")

    # -- router seams (ServingRouter routing rounds) ---------------------
    def router_round(self, store_dir: Optional[str] = None
                     ) -> List[Dict[str, Any]]:
        """Router round-boundary seam, called once per routing round. Returns
        this round's scheduled router fault actions
        ``[{"kind", "replica"}, ...]`` — the router applies them to its
        handles (kill / mute heartbeat / partition for THIS round; a held
        condition fires every round of its ``times`` window so the handle
        needs no countdown state). The first ``router_partition`` round also
        tears the newest rendezvous generation manifest (see module
        docstring)."""
        idx = self._count("router_round")
        actions: List[Dict[str, Any]] = []
        for e in self.schedule.entries:
            if e["kind"] not in ROUTER_KINDS \
                    or not self._matches_index(e, idx):
                continue
            if e["kind"] == "replica_kill":
                if e.get("_done"):
                    continue
                e["_done"] = True
            if e["kind"] == "router_partition" and store_dir \
                    and not e.get("_torn"):
                e["_torn"] = True
                self._tear_newest_manifest(store_dir)
            act = {"kind": e["kind"], "replica": int(e["replica"])}
            self._fire(e, "router_round", round=idx, **act)
            actions.append(act)
        return actions

    def kv_handoff(self, payload: Dict[str, Any]) -> None:
        """Handoff seam (disaggregated serving): called once per KV
        handoff attempt with the exported payload. "fail" raises
        HandoffFault (the router falls back to re-prefill); "corrupt"
        flips bytes in the largest payload buffer IN PLACE — the
        importing engine's crc32 check must refuse the torn payload
        typed, never scatter it."""
        idx = self._count("kv_handoff")
        for e in self.schedule.entries:
            if e["kind"] != "kv_handoff" or not self._matches_index(e, idx):
                continue
            mode = e.get("mode", "fail")
            self._fire(e, "kv_handoff", index=idx, mode=mode)
            if mode == "corrupt":
                data = payload.get("data") or {}
                if not data:
                    continue
                name = max(data, key=lambda k: data[k].nbytes)
                flat = data[name].reshape(-1).view(np.uint8)
                flat[: max(1, flat.size // 16)] ^= 0xFF
            else:
                raise HandoffFault(
                    f"injected kv_handoff failure (handoff {idx}) "
                    "(robustness.faults)")

    @staticmethod
    def _tear_newest_manifest(store_dir: str) -> None:
        """Write a TRUNCATED ``gen_<N+1>.json`` (a torn manifest write that
        never finished, NOT a ``.tmp.`` temp) so every generation read during
        the partition must fall back to the newest READABLE manifest — the
        exact ``FileRendezvous.current_generation`` walk-back PR 6 pinned.
        The next real publish heals it by replacing the same filename."""
        try:
            gens = sorted(fn for fn in os.listdir(store_dir)
                          if fn.startswith("gen_") and ".tmp." not in fn
                          and fn.endswith(".json"))
            n = (int(gens[-1][len("gen_"):-len(".json")]) + 1) if gens else 0
            with open(os.path.join(store_dir, f"gen_{n:08d}.json"), "w") as f:
                f.write('{"generation": ')          # torn mid-write
        except (OSError, ValueError):
            pass            # an unwritable store is its own fault, not ours

    # -- clock seam (rendezvous) ---------------------------------------
    def make_clock(self, base=None):
        """Wrap a clock with scheduled skew: after `after` reads, add
        ``skew_s`` seconds — the file-rendezvous sees heartbeats age out
        (host death / heartbeat loss) without any store mutation."""
        import time as _time
        base = base or _time.time
        skews = [dict(e) for e in self.schedule.entries
                 if e["kind"] == "clock_skew"]
        state = {"reads": 0}

        def clock() -> float:
            t = base()
            state["reads"] += 1
            for e in skews:
                if state["reads"] > e.get("after", 0):
                    if not e.get("_seen"):
                        e["_seen"] = True
                        self._fire(e, "clock", reads=state["reads"])
                    t += float(e.get("skew_s", 0.0))
            return t
        return clock


# -- global install (the seams consult this) ----------------------------
# The injector is PROCESS-global by design: an elastic rebuild constructs a
# fresh engine mid-run and must keep the schedule's counters. Consequence:
# a later engine with `robustness.faults.enabled: false` does NOT disarm an
# already-armed injector — call faults.clear() to stop injecting.
_ACTIVE: Optional[FaultInjector] = None
_ACTIVE_CFG_KEY: Optional[str] = None  # set only for config-armed injectors


def install(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    global _ACTIVE, _ACTIVE_CFG_KEY
    _ACTIVE = injector
    _ACTIVE_CFG_KEY = None
    return injector


def install_from_config(faults_cfg) -> Optional[FaultInjector]:
    """Engine-init hook: build + install from ``robustness.faults``. A
    rebuild with the SAME schedule keeps the live injector (counters
    survive the rescale); a DIFFERENT schedule replaces it; a manually
    install()ed injector (test harness) is never replaced."""
    global _ACTIVE_CFG_KEY
    if not getattr(faults_cfg, "enabled", False):
        return _ACTIVE
    import json as _json
    key = _json.dumps({"seed": faults_cfg.seed,
                       "entries": faults_cfg.entries},
                      sort_keys=True, default=str)
    if _ACTIVE is None or (_ACTIVE_CFG_KEY is not None
                           and _ACTIVE_CFG_KEY != key):
        if _ACTIVE is not None:
            logger.warning("robustness: replacing the active fault "
                           "injector — the config schedule changed")
        logger.warning("robustness: fault injection ENABLED "
                       f"({len(faults_cfg.entries)} scheduled entries, "
                       f"seed={faults_cfg.seed})")
        install(FaultInjector(FaultSchedule.from_config(faults_cfg)))
        _ACTIVE_CFG_KEY = key
    return _ACTIVE


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def clear() -> None:
    install(None)


def io_seam(category: str, path: Optional[str] = None,
            offset: Optional[int] = None) -> None:
    """Production-code hook: a no-op unless an injector is installed."""
    if _ACTIVE is not None:
        _ACTIVE.op(category, path, offset)


def mutate_seam(tag_dir: str) -> None:
    if _ACTIVE is not None:
        _ACTIVE.mutate_tag(tag_dir)


def serving_round_seam() -> Dict[str, Any]:
    """ServingEngine round-boundary hook: a no-op unless an injector is
    installed. May raise BackendFault or deliver SIGTERM; returns the
    round's pool squeeze decision."""
    if _ACTIVE is not None:
        return _ACTIVE.serving_round()
    return {"squeeze": None}


def dispatch_seam() -> None:
    """ServingEngine decode-dispatch hook (inside the watchdog guard)."""
    if _ACTIVE is not None:
        _ACTIVE.decode_dispatch()


def kv_handoff_seam(payload: Dict[str, Any]) -> None:
    """ServingRouter KV-handoff hook: a no-op unless an injector is
    installed. May raise HandoffFault (payload lost in flight) or corrupt
    the payload in place (torn transfer — the importer's checksum is the
    last line of defense)."""
    if _ACTIVE is not None:
        _ACTIVE.kv_handoff(payload)


def router_seam(store_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """ServingRouter round-boundary hook: a no-op (empty action list) unless
    an injector is installed. ``store_dir`` is the rendezvous store a
    ``router_partition`` tears its manifest into."""
    if _ACTIVE is not None:
        return _ACTIVE.router_round(store_dir)
    return []
