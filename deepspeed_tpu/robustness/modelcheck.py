"""graft-check: exhaustive bounded model checker for the serving
control plane.

The dynamic face of ISSUE 20 (the static face is
``analysis/proto_lint.py``): the router's circuit breaker, failover
fencing, and the fleet controller's cooldown are small state machines
with a known off-by-one history (PR 19's ``cooldown_ticks=1`` bug) —
exactly the kind of logic where a hand-picked test sequence passes and
the interleaving two events to the left loses a request. This module
drives the REAL ``ServingRouter`` and ``FleetController`` (not models
of them) with an injectable clock over ALL event interleavings up to a
bounded depth, and checks six invariants after every event:

``open-admits``
    A replica whose breaker was OPEN (or DEAD) at admission time never
    receives a new request (HALF_OPEN probe admissions are legal).
``double-serve``
    No request is completed by more than one replica (the
    migrate-AND-resubmit duplicate a fencing bug produces).
``unfenced-migration``
    A failover only happens with death evidence — an in-process kill or
    a committed drain snapshot. Heartbeat silence alone (a muted store
    writer, a torn manifest) must never migrate a live replica's work.
``lost-with-valid-drain``
    When a valid committed drain exists and a live survivor exists, a
    failover loses zero requests.
``fleet-bounds``
    The controller never scales the tier above ``max_replicas`` or
    below ``min_replicas``.
``cooldown-discipline``
    ``cooldown_ticks=N`` suppresses scale actions for EXACTLY the N
    ticks after a scale event: an action with fewer than N observe
    ticks since the last action is the PR-19 off-by-one; a clean,
    sustained-hot, below-max gap longer than N is a stuck cooldown.

The event alphabet (each event is one atomic world transition):

``probe``      one routing round (``router.step()``: serve, sweep,
               breaker walk) + one admission attempt; +1s of clock
``heartbeat``  every live, un-muted replica publishes a heartbeat
``stale``      the victim replica's heartbeat writer dies (persistent
               mute — the replica itself keeps serving) and the clock
               jumps past ``dead_after_s``; survivors re-beat
``fault``      persistent partition of the victim: dispatch to it
               raises, its queue stalls
``kill``       supervised in-process kill of the victim: drain through
               the integrity chain, then death (evidence: both)
``drain``      external SIGTERM: the victim drains itself through the
               integrity chain and exits — the router only ever sees
               the heartbeat loss and the committed tag
``torn``       a torn (uncommitted) drain tag appears in the victim's
               drain dir while it is alive — never death evidence
``tick``       one fleet-controller observation/action

Violations print as replayable event-trace ids in the graft-race
style (``e0.1.0.0`` = alphabet indexes): ``--replay`` re-runs exactly
that sequence with per-event narration.

Corpus twins (gated by ``--corpus``, surfaced through ``lint
--corpus``):

* ``fenceless-failover`` — a router that migrates on heartbeat silence
  alone (no death evidence) double-serves within depth 4 of a 4-event
  alphabet; the real fenced router holds over the full space.
* ``cooldown-off-by-one`` — the PR-19 pre-fix ``tick()`` (decrement
  before the gate) acts with zero observe ticks at
  ``cooldown_ticks=1``; the fixed controller holds.
* ``control-plane-full`` — correct-only: the shipped router +
  controller hold all six invariants over the FULL 8-event alphabet at
  the shipped depth.

Usage::

    python -m deepspeed_tpu.robustness.modelcheck --corpus
    python -m deepspeed_tpu.robustness.modelcheck --audit control-plane-full
    python -m deepspeed_tpu.robustness.modelcheck --audit fenceless-failover \\
        --defect --replay e0.1.0.0
"""

import argparse
import itertools
import json
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.analysis.report import Finding, Report

FULL_ALPHABET = ("probe", "heartbeat", "stale", "fault", "kill", "drain",
                 "torn", "tick")
#: the fencing-focused sub-alphabet (no controller in those harnesses)
FENCE_ALPHABET = ("probe", "stale", "heartbeat", "fault")
#: shipped exhaustive depth for the full alphabet (8^1..8^3 = 584 runs)
FULL_DEPTH = 3


class _Finished:
    """Just enough of a finished Request for ``router._on_finished``."""

    def __init__(self, rid: int):
        self.rid = rid
        self.first_token_t = None
        self.submit_t = None


class _Replica:
    """Pure-host stub replica implementing the ReplicaHandle protocol
    (see ``router.ReplicaHandle``) with a ground-truth life flag the
    router cannot touch: ``_failover`` writes ``rep.dead``, but only
    ``kill()``/``die_external()`` — actual deaths — clear
    ``_gt_alive``. The gap between the two is what the fencing
    invariants measure."""

    def __init__(self, name: str, store_dir: str, drain_root: str,
                 clock: Callable[[], float],
                 completions: Dict[int, List[str]],
                 capacity: int = 8, service_rate: int = 1,
                 hot: bool = False):
        import time
        from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
        self.name = name
        self.role = "both"
        self.rdzv = FileRendezvous(store_dir, name, clock=clock)
        self.drain_dir = os.path.join(drain_root, name)
        self.dead = False              # router-written
        self.partitioned = False       # router-reset each round
        self.mute_heartbeat = False    # router-reset each round
        self.killed_t: Optional[float] = None
        self.capacity = capacity
        self.service_rate = service_rate
        self.hot = hot                 # report saturated meta (tier heat)
        self._time = time
        self._q: List[int] = []
        self._part = False             # persistent partition (fault event)
        self._muted = False            # persistent heartbeat outage
        self._exited = False           # drained + exited (drain event)
        self._gt_alive = True          # ground truth, router-invisible
        self._completions = completions

    # -- registry ------------------------------------------------------

    def meta(self) -> Dict[str, Any]:
        depth = self.capacity if self.hot else len(self._q)
        return {"role": self.role, "queue_depth": depth, "running": 0,
                "capacity": self.capacity, "pool_free": 1.0,
                "draining": False}

    def publish(self) -> None:
        if self.dead or self._exited or self._muted \
                or self.mute_heartbeat or not self._gt_alive:
            return
        self.rdzv.heartbeat(meta=self.meta())

    # -- dispatch ------------------------------------------------------

    def try_admit(self, prompt, max_new_tokens: int, rid: int,
                  ttft_deadline_ms=None, deadline_ms=None) -> int:
        from deepspeed_tpu.inference.router import (ReplicaDead,
                                                    ReplicaUnreachable)
        from deepspeed_tpu.inference.scheduler import AdmissionRejected
        if self.dead or not self._gt_alive:
            raise ReplicaDead(self.name)
        if self._part or self._exited:
            raise ReplicaUnreachable(f"{self.name} unreachable")
        if len(self._q) >= self.capacity:
            raise AdmissionRejected("queue_full", replica=self.name)
        self._q.append(rid)
        return rid

    def step(self) -> List[_Finished]:
        """The replica's own serve loop. A muted replica (heartbeat
        outage) still serves — that gap is the fenceless-failover
        counterexample. A partitioned/exited one does not."""
        from deepspeed_tpu.inference.router import (ReplicaDead,
                                                    ReplicaUnreachable)
        if self.dead or not self._gt_alive and not self._exited:
            raise ReplicaDead(self.name)
        if self._exited:
            raise ReplicaUnreachable(f"{self.name} exited")
        if self._part:
            raise ReplicaUnreachable(f"{self.name} partitioned")
        done = []
        for rid in self._q[:self.service_rate]:
            self._completions.setdefault(rid, []).append(self.name)
            done.append(_Finished(rid))
        self._q = self._q[self.service_rate:]
        try:
            self.publish()
        except OSError:
            pass
        return done

    def accept_migration(self, recs, rng_counter=None, source=None,
                         geometry=None, kv=None) -> List[int]:
        rids = [int(rec["rid"]) for rec in recs]
        self._q.extend(rids)
        return rids

    def new_cancelled(self):
        return []

    def inflight(self) -> int:
        return len(self._q)

    @property
    def done(self) -> bool:
        return not self._q

    # -- deaths --------------------------------------------------------

    def _write_drain(self, commit: bool = True) -> str:
        from deepspeed_tpu.inference.schemas import DRAIN_STATE_V2
        from deepspeed_tpu.robustness import integrity
        tag_dir = os.path.join(self.drain_dir, f"drain_{self.name}")
        os.makedirs(tag_dir, exist_ok=True)
        integrity.invalidate(tag_dir)
        state = {"version": DRAIN_STATE_V2, "source": self.name,
                 "engine": {"max_model_len": 4096, "block_size": 16,
                            "table_width": 256,
                            "max_seqs": self.capacity},
                 "requests": [{"rid": rid, "prompt": [1, 2, 3],
                               "max_new_tokens": 8, "generated": []}
                              for rid in self._q]}
        integrity.atomic_write(os.path.join(tag_dir, "state.json"),
                               json.dumps(state, indent=1),
                               what="modelcheck stub drain write")
        if commit:
            integrity.write_manifest(tag_dir)
            integrity.write_commit_marker(tag_dir)
        return tag_dir

    def kill(self) -> Optional[str]:
        """Supervised in-process kill: drain, then die (the router holds
        both kinds of evidence)."""
        if self.dead or not self._gt_alive:
            return None
        self.killed_t = self._time.perf_counter()
        path = self._write_drain(commit=True)
        self._q = []
        self._gt_alive = False
        self.dead = True
        return path

    def die_external(self) -> str:
        """External SIGTERM: drain + exit. The router's ``rep.dead``
        stays False — it only ever learns from the heartbeat loss and
        the committed tag (the per-process deployment)."""
        path = self._write_drain(commit=True)
        self._q = []
        self._gt_alive = False
        self._exited = True
        return path

    def write_torn(self) -> str:
        """A torn drain tag (crashed mid-drain rewrite elsewhere, or a
        partial copy): state without manifest/commit marker. NEVER
        death evidence — the replica is still alive."""
        return self._write_drain(commit=False)


class _FencelessRouter:
    """Factory for the seeded defect twin: a router whose health sweep
    treats heartbeat silence alone as death evidence (the exact bug the
    fencing rule exists to prevent). Built lazily so importing this
    module stays light."""

    def __new__(cls, config, name: str = "router"):
        from deepspeed_tpu.inference.router import (BREAKER_DEAD,
                                                    ServingRouter)

        class _Fenceless(ServingRouter):
            def _health_sweep(self):
                self._refresh_info()
                for rname, rep in list(self.replicas.items()):
                    if self._breaker[rname]["state"] == BREAKER_DEAD:
                        continue
                    if self._heartbeat_age(rname) > self.config.dead_after_s:
                        # DEFECT: no rep.dead / snapshot evidence check
                        self._failover(rep, tag=self._drain_snapshot(rep))

        return _Fenceless(config, name)


def _prefix_controller(router, spawn, config):
    """The PR-19 pre-fix ``FleetController.tick()``: the cooldown
    decrement happens BEFORE the gate is computed, so
    ``cooldown_ticks=1`` suppresses zero ticks (the seeded defect the
    cooldown-discipline invariant must find)."""
    from deepspeed_tpu.inference.fleet import FleetController

    class _PreFix(FleetController):
        def tick(self):
            cfg = self.config
            self._counters["ticks"] += 1
            if self._cooldown > 0:
                self._cooldown -= 1          # off-by-one: decrement first
            cooling = self._cooldown > 0
            tier = self._tier()
            self._last_tier = len(tier)
            if not tier:
                self._last_load = 0.0
                self._hot = self._idle = 0
                if cfg.min_replicas > 0 and not cooling:
                    return self._scale_up(reason="below_min")
                return None
            load = sum(self._load(m) for m in tier.values()) / len(tier)
            self._last_load = load
            if load >= cfg.scale_up_load:
                self._hot += 1
                self._idle = 0
            elif load <= cfg.scale_down_load:
                self._idle += 1
                self._hot = 0
            else:
                self._hot = self._idle = 0
            if cooling:
                return None
            if len(tier) < cfg.min_replicas:
                return self._scale_up(reason="below_min")
            if self._hot >= cfg.scale_up_after \
                    and len(tier) < cfg.max_replicas:
                return self._scale_up(reason="sustained_pressure",
                                      load=load)
            if self._idle >= cfg.scale_down_after \
                    and len(tier) > cfg.min_replicas:
                victim = min(tier, key=lambda h: self._load(tier[h]))
                return self._scale_down(victim, load=load)
            return None

    return _PreFix(router, spawn, config)


class Harness:
    """One world the explorer drives: a real router (+ optional
    controller) over stub replicas with an injected clock, checking the
    six invariants after every event. Events target the victim ``r0``;
    ``r1`` (and any autoscaled replica) survives."""

    def __init__(self, base_dir: str,
                 fenced: bool = True,
                 controller: bool = False,
                 prefix_cooldown: bool = False,
                 cooldown_ticks: int = 2,
                 hot: bool = False,
                 dead_after_s: float = 2.5,
                 min_replicas: int = 1,
                 max_replicas: int = 4):
        from deepspeed_tpu.inference.fleet import (FleetConfig,
                                                   FleetController)
        from deepspeed_tpu.inference.router import (RouterConfig,
                                                    ServingRouter)
        from deepspeed_tpu.robustness import events as rb_events
        self._rb = rb_events
        rb_events.clear()
        self.base = base_dir
        store = os.path.join(base_dir, "store")
        drains = os.path.join(base_dir, "drains")
        self.t = [0.0]
        clock = lambda: self.t[0]  # noqa: E731 — injectable model time
        self.completions: Dict[int, List[str]] = {}
        cfg = RouterConfig(store_dir=store, drain_dir=drains,
                           dead_after_s=dead_after_s, breaker=True,
                           breaker_faults=2, breaker_probe_after=1,
                           clock=clock)
        router_cls = ServingRouter if fenced else _FencelessRouter
        self.router = router_cls(cfg)
        self._mk = lambda name: _Replica(name, store, drains, clock,
                                         self.completions, hot=hot)
        self.victim = self._mk("r0")
        self.router.register_handle(self.victim)
        self.router.register_handle(self._mk("r1"))
        self.ctl = None
        self.fleet_cfg = None
        if controller:
            self.fleet_cfg = FleetConfig(
                min_replicas=min_replicas, max_replicas=max_replicas,
                scale_up_after=1, cooldown_ticks=cooldown_ticks,
                role="both", dead_after_s=dead_after_s)
            spawn = lambda name, role: self._mk(name)  # noqa: E731
            maker = (_prefix_controller if prefix_cooldown
                     else FleetController)
            self.ctl = maker(self.router, spawn, self.fleet_cfg)
        self.hot = hot
        self.violations: List[str] = []
        self.trace: List[str] = []
        self._reported: set = set()
        self._failover_seen = 0
        self._scale_seen = {"fleet_scale_up": 0, "fleet_scale_down": 0}
        # cooldown bookkeeping: observe ticks since the last action
        # (None until the first action) + whether the gap is "clean"
        # (tick-only, so the exactness half of the invariant applies)
        self._since_action: Optional[int] = None
        self._gap_clean = True

    # -- events --------------------------------------------------------

    def apply(self, event: str) -> None:
        self.trace.append(event)
        if event != "tick":
            self._gap_clean = False
        getattr(self, f"_ev_{event}")()
        self._check()

    def _ev_probe(self) -> None:
        from deepspeed_tpu.inference.router import (BREAKER_DEAD,
                                                    BREAKER_OPEN)
        from deepspeed_tpu.inference.scheduler import AdmissionRejected
        self.t[0] += 1.0
        self.router.step()
        blocked = {n for n in self.router.replicas
                   if self.router.breaker_state(n)
                   in (BREAKER_OPEN, BREAKER_DEAD)}
        try:
            rid = self.router.add_request([1, 2, 3], max_new_tokens=4)
        except AdmissionRejected:
            return
        placed = self.router._placement.get(rid)
        if placed in blocked:
            self.violations.append(
                f"open-admits: request {rid} admitted to replica "
                f"{placed} whose breaker was "
                f"{self.router.breaker_state(placed)}")

    def _ev_heartbeat(self) -> None:
        for rep in self.router.replicas.values():
            rep.publish()

    def _ev_stale(self) -> None:
        # the victim's heartbeat writer dies (replica keeps serving);
        # survivors re-beat across the staleness jump
        self.victim._muted = True
        self.t[0] += self.router.config.dead_after_s + 0.1
        for rep in self.router.replicas.values():
            rep.publish()

    def _ev_fault(self) -> None:
        self.victim._part = True

    def _ev_kill(self) -> None:
        self.victim.kill()

    def _ev_drain(self) -> None:
        if self.victim._gt_alive:
            self.victim.die_external()

    def _ev_torn(self) -> None:
        if self.victim._gt_alive:
            self.victim.write_torn()

    def _ev_tick(self) -> None:
        if self.ctl is None:
            return
        acted = self.ctl.tick() is not None
        cfg = self.fleet_cfg
        if acted:
            if self._since_action is not None \
                    and self._since_action < cfg.cooldown_ticks:
                self.violations.append(
                    "cooldown-discipline: scale action after only "
                    f"{self._since_action} observe tick(s) — "
                    f"cooldown_ticks={cfg.cooldown_ticks} must suppress "
                    f"exactly {cfg.cooldown_ticks}")
            self._since_action = 0
            self._gap_clean = True
        elif self._since_action is not None:
            self._since_action += 1
            if (self._gap_clean and self.hot
                    and self._since_action > cfg.cooldown_ticks
                    and self.ctl._last_tier < cfg.max_replicas
                    and self.ctl._last_load >= cfg.scale_up_load):
                self.violations.append(
                    "cooldown-discipline: sustained pressure below "
                    f"max_replicas but no action "
                    f"{self._since_action} tick(s) after the cooldown "
                    f"(cooldown_ticks={cfg.cooldown_ticks}) — stuck")

    # -- invariants ----------------------------------------------------

    def _check(self) -> None:
        for rid, servers in self.completions.items():
            if len(servers) > 1 and ("ds", rid) not in self._reported:
                self._reported.add(("ds", rid))
                self.violations.append(
                    f"double-serve: request {rid} completed by "
                    f"{servers} — served more than once")
        failovers = self._rb.history("replica_failover")
        for ev in failovers[self._failover_seen:]:
            name = ev.get("replica")
            rep = self.router.replicas.get(name)
            if rep is not None and getattr(rep, "_gt_alive", False):
                self.violations.append(
                    f"unfenced-migration: replica {name} failed over "
                    "while alive (no death evidence — heartbeat silence "
                    "or a torn tag is not evidence)")
            survivors = any(
                getattr(r, "_gt_alive", False) and n != name
                for n, r in self.router.replicas.items())
            if ev.get("lost", 0) > 0 and survivors and rep is not None \
                    and not getattr(rep, "_gt_alive", True) \
                    and ev.get("drain_tag"):
                self.violations.append(
                    f"lost-with-valid-drain: failover of {name} lost "
                    f"{ev['lost']} request(s) despite a valid drain "
                    f"({ev['drain_tag']}) and a live survivor")
        self._failover_seen = len(failovers)
        if self.fleet_cfg is not None:
            ups = self._rb.history("fleet_scale_up")
            for ev in ups[self._scale_seen["fleet_scale_up"]:]:
                if ev.get("tier", 0) > self.fleet_cfg.max_replicas:
                    self.violations.append(
                        f"fleet-bounds: scale_up to tier {ev['tier']} > "
                        f"max_replicas={self.fleet_cfg.max_replicas}")
            self._scale_seen["fleet_scale_up"] = len(ups)
            downs = self._rb.history("fleet_scale_down")
            for ev in downs[self._scale_seen["fleet_scale_down"]:]:
                if ev.get("tier", 0) < self.fleet_cfg.min_replicas:
                    self.violations.append(
                        f"fleet-bounds: scale_down to tier {ev['tier']} "
                        f"< min_replicas={self.fleet_cfg.min_replicas}")
            self._scale_seen["fleet_scale_down"] = len(downs)

    def close(self) -> None:
        self._rb.clear()


# ---------------------------------------------------------------------------
# the explorer
# ---------------------------------------------------------------------------

def trace_id(idxs: Sequence[int]) -> str:
    return "e" + ".".join(str(i) for i in idxs)


def parse_trace(tid: str) -> List[int]:
    if not tid.startswith("e"):
        raise ValueError(f"trace id {tid!r}: expected e<i>.<i>...")
    return [int(x) for x in tid[1:].split(".")]


def run_sequence(factory: Callable[[str], Harness],
                 alphabet: Sequence[str],
                 idxs: Sequence[int], base_dir: str,
                 narrate: bool = False) -> List[str]:
    """Run one event sequence on a fresh world to completion; returns
    every invariant violation observed along it (a fencing bug fires
    unfenced-migration one event before the duplicate completion lands,
    so a sequence can carry several)."""
    h = factory(base_dir)
    try:
        for step, i in enumerate(idxs):
            before = len(h.violations)
            h.apply(alphabet[i])
            if narrate:
                load = {n: r.inflight()
                        for n, r in h.router.replicas.items()}
                print(f"  [{step}] {alphabet[i]:<10} inflight={load}")
                for v in h.violations[before:]:
                    print(f"        -> {v}")
        return list(h.violations)
    finally:
        h.close()


def explore(factory: Callable[[str], Harness],
            alphabet: Sequence[str], depth: int,
            until_rule: Optional[str] = None) -> Dict[str, Any]:
    """Exhaustively run every event sequence of length 1..depth. Each
    sequence gets a fresh world (fresh store/drain dirs) — replay is
    exact by construction, so every failure is a replayable trace id.
    With ``until_rule`` (defect-twin mode) exploration stops at the
    first sequence whose violations include that rule; without it the
    whole space runs and every failure is collected."""
    import logging as _logging
    import shutil
    import tempfile
    from deepspeed_tpu.utils.logging import logger
    explored = 0
    failures: List[Dict[str, Any]] = []
    root = tempfile.mkdtemp(prefix="modelcheck_")
    prev = logger.level
    logger.setLevel(_logging.ERROR)
    try:
        for length in range(1, depth + 1):
            for idxs in itertools.product(range(len(alphabet)),
                                          repeat=length):
                base = os.path.join(root, f"w{explored}")
                violations = run_sequence(factory, alphabet, idxs, base)
                explored += 1
                shutil.rmtree(base, ignore_errors=True)
                if violations:
                    failures.append(
                        {"trace": trace_id(idxs),
                         "events": [alphabet[i] for i in idxs],
                         "violations": violations})
                    if until_rule is not None and any(
                            _rule_of(v) == until_rule
                            for v in violations):
                        return {"explored": explored,
                                "failures": failures, "depth": depth,
                                "alphabet": list(alphabet)}
        return {"explored": explored, "failures": failures,
                "depth": depth, "alphabet": list(alphabet)}
    finally:
        logger.setLevel(prev)
        shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# seeded audits (defect must fire / corrected must hold)
# ---------------------------------------------------------------------------

def _fence_factory(fenced: bool):
    return lambda base: Harness(base, fenced=fenced, controller=False)


def _cooldown_factory(prefix: bool):
    return lambda base: Harness(base, controller=True,
                                prefix_cooldown=prefix, cooldown_ticks=1,
                                hot=True)


def _full_factory(base: str) -> Harness:
    return Harness(base, controller=True, cooldown_ticks=2, hot=True)


#: name -> (defect factory | None, correct factory, alphabet, depth,
#:          rule the defect must fire)
_AUDITS: Dict[str, Tuple[Optional[Callable], Callable,
                         Sequence[str], int, Optional[str]]] = {
    "fenceless-failover": (_fence_factory(False), _fence_factory(True),
                           FENCE_ALPHABET, 4, "double-serve"),
    "cooldown-off-by-one": (_cooldown_factory(True),
                            _cooldown_factory(False),
                            ("tick",), 4, "cooldown-discipline"),
    "control-plane-full": (None, _full_factory, FULL_ALPHABET,
                           FULL_DEPTH, None),
}


def _rule_of(violation: str) -> str:
    return violation.split(":", 1)[0]


def audit_events(name: str, correct: bool = False,
                 depth: Optional[int] = None) -> Report:
    """Run one seeded audit; the Report mirrors graft-race's
    ``audit_schedules`` shape — findings carry a replayable trace id,
    and a defect twin that does NOT fire yields ``explorer-miss``."""
    defect_factory, correct_factory, alphabet, d, rule = _AUDITS[name]
    depth = depth or d
    factory = correct_factory if correct else defect_factory
    if factory is None:
        factory = correct_factory
        correct = True
    result = explore(factory, alphabet, depth,
                     until_rule=None if correct else rule)
    rep = Report()
    rep.meta["audit"] = {"name": name, "correct": correct,
                         "depth": depth, "alphabet": list(alphabet),
                         "explored": result["explored"]}
    for fail in result["failures"]:
        for violation in fail["violations"]:
            rep.findings.append(Finding(
                rule=_rule_of(violation),
                message=(f"{violation} [trace {fail['trace']}: "
                         f"{' -> '.join(fail['events'])}] "
                         f"(replay: --audit {name}"
                         f"{'' if correct else ' --defect'} "
                         f"--replay {fail['trace']})"),
                program=name, ident=fail["trace"],
                data={"replay_id": fail["trace"],
                      "events": fail["events"],
                      "explored": result["explored"]}))
    if not correct and not result["failures"]:
        rep.findings.append(Finding(
            rule="explorer-miss",
            message=(f"{name}: seeded defect twin explored "
                     f"{result['explored']} sequence(s) to depth {depth} "
                     "without a violation — the explorer lost its "
                     "regression floor"),
            program=name, ident="miss"))
    return rep


def replay(name: str, tid: str, correct: bool = False) -> List[str]:
    """Re-run one trace with per-event narration; returns violations."""
    import logging as _logging
    import shutil
    import tempfile
    from deepspeed_tpu.utils.logging import logger
    defect_factory, correct_factory, alphabet, _, _ = _AUDITS[name]
    factory = correct_factory if correct or defect_factory is None \
        else defect_factory
    idxs = parse_trace(tid)
    base = tempfile.mkdtemp(prefix="modelcheck_replay_")
    prev = logger.level
    logger.setLevel(_logging.ERROR)
    try:
        violations = run_sequence(factory, alphabet, idxs, base,
                                  narrate=True)
        for v in violations:
            print(f"  VIOLATION {v}")
        if not violations:
            print("  (no violation on this trace)")
        return violations
    finally:
        logger.setLevel(prev)
        shutil.rmtree(base, ignore_errors=True)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _run_corpus_gate(depth_override: Optional[int] = None) -> int:
    """Every seeded defect must FIRE (with a replayable trace) and
    every corrected twin must hold over its full bounded space."""
    rc = 0
    for name, (defect, _, alphabet, depth, rule) in _AUDITS.items():
        depth = depth_override or depth
        if defect is not None:
            rep = audit_events(name, correct=False, depth=depth)
            fired = {f.rule for f in rep.findings}
            if rule in fired:
                f = next(f for f in rep.findings if f.rule == rule)
                print(f"[check] {name}: defect twin FIRES {rule} "
                      f"(replay: --audit {name} --defect --replay "
                      f"{f.data['replay_id']})")
            else:
                rc = 1
                print(f"[check] {name}: EXPLORER MISS — defect twin did "
                      f"not fire {rule} (fired: {sorted(fired)})")
        cor = audit_events(name, correct=True, depth=depth)
        if cor.ok:
            print(f"[check] {name}: corrected twin holds over "
                  f"{cor.meta['audit']['explored']} sequence(s) "
                  f"(depth {depth}, {len(alphabet)} events)")
        else:
            rc = 1
            print(f"[check] {name}: REGRESSION — invariant violated in "
                  "the corrected twin:")
            for f in cor.findings:
                print(f"  {f.message}")
    print("modelcheck: " + ("OK" if rc == 0 else "FAIL"))
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="modelcheck",
        description="exhaustive bounded control-plane model checker")
    p.add_argument("--corpus", action="store_true",
                   help="run the seeded defect/corrected twin gate")
    p.add_argument("--list-corpus", action="store_true")
    p.add_argument("--audit", help="run one audit by name")
    p.add_argument("--defect", action="store_true",
                   help="run the audit's defect twin (default: corrected)")
    p.add_argument("--depth", type=int, default=None)
    p.add_argument("--replay", help="replay one trace id (e0.1.2)")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)

    if args.list_corpus:
        for name in sorted(_AUDITS):
            print(name)
        return 0
    if args.audit and args.replay:
        violations = replay(args.audit, args.replay,
                            correct=not args.defect)
        return 1 if violations else 0
    if args.audit:
        rep = audit_events(args.audit, correct=not args.defect,
                           depth=args.depth)
        if args.as_json:
            print(rep.to_json())
        else:
            a = rep.meta["audit"]
            print(f"[check] {args.audit}: explored {a['explored']} "
                  f"sequence(s) to depth {a['depth']}")
            for f in rep.findings:
                print(f.message)
            print("modelcheck: " + ("OK" if rep.ok else "FAIL"))
        return 0 if rep.ok else 1
    return _run_corpus_gate(args.depth)


if __name__ == "__main__":
    sys.exit(main())
