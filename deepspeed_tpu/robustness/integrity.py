"""Checkpoint integrity chain: per-tag manifest, commit marker, walk-back.

Reference: the reference's ``load_checkpoint`` trusts the ``latest`` file
completely — a torn save (crash between the tensor write and ``latest``, a
truncated shard, bitrot on shared storage) bricks the resume path with an
opaque deserialization error. Here every committed tag carries:

  ``manifest.json``  — relpath -> {size, sha256} for every file in the tag
                       dir, written AFTER the payload is durable
  ``COMMITTED``      — a tiny marker written atomically LAST; its absence
                       means the save never finished (torn)

``validate_tag`` checks marker -> manifest -> sizes -> checksums, and
``newest_valid_tag`` walks tags newest-first so ``load_checkpoint(tag=None)``
can fall back past a corrupt/uncommitted ``latest`` to the newest save that
still verifies (emitting a ``ckpt_fallback`` event) instead of raising.
``prune_tags`` bounds retention to the last K *good* tags — invalid tags are
never counted toward K (they are fallback evidence, not capacity).

Tags written before this chain existed (no manifest, no marker) validate as
``legacy``: they cannot be judged, so the loader still tries them.
"""

import hashlib
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

MANIFEST_FILE = "manifest.json"
COMMIT_FILE = "COMMITTED"
_INTEGRITY_FILES = (MANIFEST_FILE, COMMIT_FILE)


def _tag_files(tag_dir: str) -> List[str]:
    """Relpaths of every payload file under the tag dir (integrity files and
    atomic-write temps excluded; temps are in-flight, not payload)."""
    out = []
    for root, _dirs, files in os.walk(tag_dir):
        for fn in files:
            rel = os.path.relpath(os.path.join(root, fn), tag_dir)
            if rel in _INTEGRITY_FILES or ".tmp" in fn:
                continue
            out.append(rel)
    return sorted(out)


def file_digest(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def atomic_write(path: str, data: str, *, what: str) -> None:
    """THE atomic small-file write of the checkpoint chain (tmp + fsync +
    rename), shared by manifest/marker/meta/latest/pointer writers so every
    one of them gets the same bounded retry on transient errors and the
    same ``ckpt_io`` fault-injection seam."""
    from deepspeed_tpu.robustness import faults as rb_faults
    from deepspeed_tpu.robustness.retry import retry_io

    def do():
        rb_faults.io_seam("ckpt_io", path)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    retry_io(do, what=what, path=path)


def _atomic_json(path: str, obj, *, what: str) -> None:
    atomic_write(path, json.dumps(obj, indent=1), what=what)


def write_manifest(tag_dir: str, *, checksums: bool = True) -> Dict:
    """Hash the tag dir's current payload into ``manifest.json``. Call only
    after the payload is durable (checkpoint finalize)."""
    entries = {}
    for rel in _tag_files(tag_dir):
        p = os.path.join(tag_dir, rel)
        entries[rel] = {"size": os.path.getsize(p),
                        "sha256": file_digest(p) if checksums else None}
    manifest = {"version": 1, "ts": time.time(), "files": entries}
    _atomic_json(os.path.join(tag_dir, MANIFEST_FILE), manifest,
                 what="checkpoint manifest write")
    return manifest


def write_commit_marker(tag_dir: str) -> None:
    """The atomic 'this save finished' bit — written LAST."""
    _atomic_json(os.path.join(tag_dir, COMMIT_FILE),
                 {"ts": time.time(), "tag": os.path.basename(tag_dir)},
                 what="checkpoint commit-marker write")


def invalidate(tag_dir: str, *, drop_manifest: bool = False) -> None:
    """Drop the commit marker before rewriting a tag in place, so a crash
    mid-overwrite reads as torn rather than silently mixing two saves.
    drop_manifest=True also removes the manifest — required when the NEW
    save will not write one (integrity disabled), otherwise the stale
    manifest would make the finished save validate as uncommitted forever
    instead of falling back to the legacy rescue."""
    try:
        os.remove(os.path.join(tag_dir, COMMIT_FILE))
    except FileNotFoundError:
        pass
    if drop_manifest:
        try:
            os.remove(os.path.join(tag_dir, MANIFEST_FILE))
        except FileNotFoundError:
            pass


def is_committed(tag_dir: str) -> bool:
    return os.path.exists(os.path.join(tag_dir, COMMIT_FILE))


def validate_tag(tag_dir: str, *, deep: bool = True) -> Tuple[bool, str]:
    """(ok, reason). ``deep`` re-hashes content; shallow checks existence and
    sizes only (enough for truncation, not bitrot)."""
    if not os.path.isdir(tag_dir):
        return False, "missing"
    manifest_path = os.path.join(tag_dir, MANIFEST_FILE)
    if not is_committed(tag_dir):
        # pre-integrity saves have no manifest/marker but DID finish their
        # finalize (meta.json is written after the payload is durable) —
        # those can't be judged, so the loader still tries them. A tag with
        # neither meta nor manifest is a torn in-progress save: skip it.
        if not os.path.exists(manifest_path) and any(
                os.path.exists(os.path.join(tag_dir, m))
                for m in ("meta.json", "infinity_meta.json")):
            return True, "legacy"
        return False, "uncommitted"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError) as e:
        return False, f"manifest-unreadable: {e}"
    for rel, want in files.items():
        p = os.path.join(tag_dir, rel)
        if not os.path.exists(p):
            return False, f"missing-file: {rel}"
        if os.path.getsize(p) != want["size"]:
            return False, f"size-mismatch: {rel}"
        if deep and want.get("sha256") and file_digest(p) != want["sha256"]:
            return False, f"checksum-mismatch: {rel}"
    return True, "ok"


def _tag_mtime(tag_dir: str) -> float:
    """Recency key: commit-marker mtime when present, else the dir's."""
    for probe in (os.path.join(tag_dir, COMMIT_FILE),
                  os.path.join(tag_dir, MANIFEST_FILE), tag_dir):
        try:
            return os.path.getmtime(probe)
        except OSError:
            continue
    return 0.0


def list_tags(load_dir: str) -> List[str]:
    """Tag names under load_dir, newest first."""
    try:
        names = [n for n in os.listdir(load_dir)
                 if os.path.isdir(os.path.join(load_dir, n))]
    except OSError:
        return []
    return sorted(names, key=lambda n: _tag_mtime(os.path.join(load_dir, n)),
                  reverse=True)


def newest_valid_tag(load_dir: str, *, exclude: Iterable[str] = (),
                     deep: bool = True) -> Optional[str]:
    """Walk tags newest-first; return the first that validates."""
    excluded = set(exclude)
    for name in list_tags(load_dir):
        if name in excluded:
            continue
        ok, reason = validate_tag(os.path.join(load_dir, name), deep=deep)
        if ok:
            return name
        logger.warning(f"checkpoint integrity: skipping tag '{name}' "
                       f"({reason})")
    return None


def prune_tags(load_dir: str, keep_last_k: int,
               protect: Iterable[str] = ()) -> List[str]:
    """Delete committed-valid tags beyond the newest ``keep_last_k``.
    Invalid/uncommitted tags are left alone (they never count toward K and
    may still be wanted as post-mortem evidence); ``protect`` (e.g. the tag
    ``latest`` names) is never deleted. Returns the deleted tag names."""
    if keep_last_k <= 0:
        return []
    import shutil
    protected = set(protect)
    good = [n for n in list_tags(load_dir)
            if validate_tag(os.path.join(load_dir, n), deep=False)[0]]
    deleted = []
    for name in good[keep_last_k:]:
        if name in protected:
            continue
        shutil.rmtree(os.path.join(load_dir, name), ignore_errors=True)
        deleted.append(name)
        logger.info(f"checkpoint retention: pruned tag '{name}' "
                    f"(keep_last_k={keep_last_k})")
    return deleted
