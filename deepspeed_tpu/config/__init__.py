from deepspeed_tpu.config.config import (
    Config, OptimizerConfig, SchedulerConfig, FP16Config, BF16Config,
    ZeroConfig, OffloadDeviceConfig, PipelineConfig, TensorParallelConfig,
    SequenceParallelConfig, MoEConfig, MeshConfig, ActivationCheckpointingConfig,
    FlopsProfilerConfig, CommsLoggerConfig, AIOConfig, CheckpointConfig,
    ElasticityConfig, AutotuningConfig, CurriculumConfig, CompressionConfig,
    AnalysisConfig, TelemetryConfig, TelemetryTraceConfig, AnomalyConfig,
    MonitorSinkConfig,
)
from deepspeed_tpu.config.config_utils import ConfigError, ConfigModel
