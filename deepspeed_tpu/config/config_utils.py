"""Config plumbing: a small typed-config base over dataclasses.

Reference: ``deepspeed/runtime/config_utils.py`` (``DeepSpeedConfigModel`` on
pydantic, with deprecated-field machinery). We use plain dataclasses with a
recursive ``from_dict`` so the config surface is declared once and validated
eagerly; unknown keys warn (the reference errors on some, ignores others —
warning keeps user configs portable).
"""

import dataclasses
import json
from typing import Any, Dict, Type, TypeVar, get_args, get_origin, get_type_hints

from deepspeed_tpu.utils.logging import logger

T = TypeVar("T", bound="ConfigModel")


class ConfigError(ValueError):
    pass


@dataclasses.dataclass
class ConfigModel:
    """Base for all config sections; subclass as a @dataclass."""

    # Map of json_key -> field_name overrides (e.g. "type" -> "name").
    _aliases: Dict[str, str] = None  # type: ignore[assignment]

    @classmethod
    def from_dict(cls: Type[T], data: Dict[str, Any], path: str = "") -> T:
        if data is None:
            data = {}
        if not isinstance(data, dict):
            raise ConfigError(f"{path or cls.__name__}: expected a dict, got {type(data).__name__}")
        hints = get_type_hints(cls)
        field_names = {f.name for f in dataclasses.fields(cls) if f.name != "_aliases"}
        aliases = getattr(cls, "ALIASES", {})
        kwargs = {}
        for key, value in data.items():
            name = aliases.get(key, key)
            if name not in field_names:
                logger.warning(f"config: unknown key '{path}{key}' (ignored)")
                continue
            hint = hints.get(name)
            kwargs[name] = _coerce(hint, value, f"{path}{key}.")
        obj = cls(**kwargs)  # type: ignore[call-arg]
        object.__setattr__(obj, "_explicit_keys", frozenset(kwargs))
        obj.validate()
        return obj

    def was_set(self, field_name: str) -> bool:
        """True when the user's dict explicitly provided this field (a
        default-constructed section reports False for everything). Lets
        callers distinguish 'reference default' from 'user asked for it'."""
        return field_name in getattr(self, "_explicit_keys", ())

    def validate(self) -> None:
        """Override for cross-field checks."""

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):
            if f.name == "_aliases":
                continue
            value = getattr(self, f.name)
            if isinstance(value, ConfigModel):
                out[f.name] = value.to_dict()
            else:
                out[f.name] = value
        return out

    def __repr__(self):
        return f"{type(self).__name__}({json.dumps(self.to_dict(), default=str, indent=2)})"


def _coerce(hint, value, path: str):
    """Best-effort coercion of a raw JSON value to the annotated type."""
    if hint is None or value is None:
        return value
    origin = get_origin(hint)
    if origin is not None:
        # Optional[X] / Union — try the non-None arm if it's a ConfigModel
        for arg in get_args(hint):
            if isinstance(arg, type) and issubclass(arg, ConfigModel) and isinstance(value, dict):
                return arg.from_dict(value, path)
        return value
    if isinstance(hint, type) and issubclass(hint, ConfigModel):
        return hint.from_dict(value if isinstance(value, dict) else {}, path)
    if hint is float and isinstance(value, int):
        return float(value)
    if hint is int and isinstance(value, float) and value.is_integer():
        return int(value)
    if hint is bool and isinstance(value, str):
        return value.lower() in ("true", "1", "yes")
    return value


def config_field(default=None, **kw):
    if isinstance(default, (dict, list, set)) or (isinstance(default, type) and issubclass(default, ConfigModel)):
        if isinstance(default, type):
            return dataclasses.field(default_factory=default, **kw)
        d = default
        return dataclasses.field(default_factory=lambda: type(d)(d), **kw)
    return dataclasses.field(default=default, **kw)
