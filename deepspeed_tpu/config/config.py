"""The framework config tree.

Reference: ``deepspeed/runtime/config.py:658`` (``DeepSpeedConfig``) plus the
pydantic sub-configs (zero ``runtime/zero/config.py:76``, offload
``offload_config.py:20,51``, fp16/bf16 getters ``runtime/config.py:118-640``,
monitor ``monitor/config.py``, comms ``comm/config.py``, aio/flops-profiler
sections). Same JSON key surface where the concept survives on TPU; new
TPU-only keys (mesh/tensor_parallel/sequence_parallel/remat) are additive.

The batch triad solve (train_batch = micro_batch × grad_accum × dp_world) is
preserved exactly (reference: ``runtime/config.py`` batch reconciliation).
"""

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

from deepspeed_tpu.config.config_utils import ConfigModel, ConfigError, config_field
from deepspeed_tpu.utils.logging import logger


# --------------------------------------------------------------------------
# Sub-sections
# --------------------------------------------------------------------------

@dataclasses.dataclass
class OptimizerConfig(ConfigModel):
    ALIASES = {"type": "name"}
    name: str = "adamw"
    params: Dict[str, Any] = config_field({})

    def validate(self):
        from deepspeed_tpu.ops.registry import SUPPORTED_OPTIMIZERS
        if self.name.lower() not in SUPPORTED_OPTIMIZERS:
            raise ConfigError(f"optimizer '{self.name}' not supported; "
                              f"choose from {sorted(SUPPORTED_OPTIMIZERS)}")


@dataclasses.dataclass
class SchedulerConfig(ConfigModel):
    ALIASES = {"type": "name"}
    name: Optional[str] = None
    params: Dict[str, Any] = config_field({})


@dataclasses.dataclass
class FP16Config(ConfigModel):
    """Reference keys: ``runtime/config.py`` fp16 section + ``fp16/loss_scaler.py:84``."""
    enabled: bool = False
    loss_scale: float = 0.0            # 0 = dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    auto_cast: bool = True

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == 0.0


@dataclasses.dataclass
class BF16Config(ConfigModel):
    enabled: bool = True  # TPU-first default: bf16 on


@dataclasses.dataclass
class OffloadDeviceConfig(ConfigModel):
    """Reference: ``runtime/zero/offload_config.py:20,51`` (DeepSpeedZeroOffload{Param,Optimizer}Config)."""
    device: str = "none"              # none | cpu | nvme  (cpu == TPU-VM host DRAM)
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    pin_memory: bool = False
    # overlapped offload pipeline (the reference's pipelined optimizer
    # swapper defaults these OFF; here the double-buffered layer streaming /
    # three-way read(i+1) || update(i) || write(i-1) schedule IS the
    # supported fast path, so both default ON — setting BOTH knobs of an
    # offload section to False gets the fully-drained executor/swapper,
    # e.g. for bit-for-bit pipeline bisection)
    pipeline_read: bool = True
    pipeline_write: bool = True
    fast_init: bool = False
    max_in_cpu: int = 1_000_000_000
    ratio: float = 1.0
    # run the optimizer ON the host over host-resident fp32 state (native
    # fused CPU-Adam, the reference's DeepSpeedCPUAdam design): per step only
    # compute-dtype grads/params cross the bus. Opt-in because a remote-relay
    # dev setup pays the wire for the grad hop; on a real TPU-VM this is the
    # intended ZeRO-Offload tier.
    use_cpu_adam: bool = False

    @property
    def enabled(self) -> bool:
        return self.device not in ("none", None)


@dataclasses.dataclass
class ZeroConfig(ConfigModel):
    """Reference: ``runtime/zero/config.py:76`` (DeepSpeedZeroConfig).

    On TPU, stages are realized as sharding rules over the mesh's data/fsdp
    axes rather than a partitioned-tensor runtime:
      stage 0 — pure DP (replicated params/grads/opt, psum grads)
      stage 1 — optimizer states sharded over data axis
      stage 2 — + gradients reduce-scattered (psum_scatter)
      stage 3 — + parameters sharded, all-gathered on use by GSPMD
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    offload_param: OffloadDeviceConfig = config_field(OffloadDeviceConfig)
    offload_optimizer: OffloadDeviceConfig = config_field(OffloadDeviceConfig)
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = 1
    elastic_checkpoint: bool = False

    def validate(self):
        if self.stage not in (0, 1, 2, 3):
            raise ConfigError(f"zero_optimization.stage must be 0..3, got {self.stage}")


@dataclasses.dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """Reference: ``runtime/activation_checkpointing/checkpointing.py:789``
    (configure). On TPU this maps to jax.checkpoint/remat policies;
    partition_activations maps to saving activations sharded over the tensor
    axis (GSPMD keeps them sharded when the policy saves them)."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False        # offload saved activations to host
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native additions
    policy: str = "none"   # none | full | dots_saveable | save_nothing | offload_dots


@dataclasses.dataclass
class PipelineConfig(ConfigModel):
    stages: int = 1                      # pipeline-parallel degree
    partition_method: str = "parameters"  # parameters | uniform | type:<regex>
    micro_batches: Optional[int] = None   # defaults to gradient_accumulation_steps
    activation_checkpoint_interval: int = 0
    schedule: str = "1f1b"                # 1f1b | gpipe | interleaved
    # --- async STEP pipeline (engine.train_batches; orthogonal to the
    # stage-parallel knobs above). The reference hides dispatch behind CUDA
    # streams; here XLA async dispatch does it — these bound/amplify it.
    in_flight: int = 2       # dispatched-steps window train_batches keeps open
    prefetch: bool = True    # double-buffered device_put of batch N+1
    fuse_steps: int = 1      # K>1: unroll K optimizer steps into ONE dispatch

    def validate(self):
        if self.in_flight < 1:
            raise ConfigError("pipeline.in_flight must be >= 1")
        if self.fuse_steps < 1:
            raise ConfigError("pipeline.fuse_steps must be >= 1")


@dataclasses.dataclass
class TensorParallelConfig(ConfigModel):
    ALIASES = {"size": "tp_size", "tp": "tp_size"}
    tp_size: int = 1
    seq_parallel: bool = False  # shard activations along sequence on the tensor axis


@dataclasses.dataclass
class SequenceParallelConfig(ConfigModel):
    """Context/sequence parallelism (absent in reference v0.8.3 — SURVEY §2.7;
    first-class here): ring attention over the 'seq' mesh axis."""
    ALIASES = {"size": "sp_size"}
    sp_size: int = 1
    mode: str = "ring"  # ring | allgather


@dataclasses.dataclass
class MoEConfig(ConfigModel):
    enabled: bool = False
    expert_parallel_size: int = 1
    num_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None   # None | 'Jitter' | 'RSample'
    drop_tokens: bool = True
    use_residual: bool = False                # PR-MoE
    aux_loss_weight: float = 0.01


@dataclasses.dataclass
class MonitorSinkConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"
    # wandb extras
    team: Optional[str] = None
    group: Optional[str] = None
    project: Optional[str] = None


@dataclasses.dataclass
class FlopsProfilerConfig(ConfigModel):
    """Reference: ``profiling/flops_profiler`` config keys, plus the
    TPU-native measured tier: ``measure_trace`` joins a real
    ``jax.profiler`` traced step (profiling/capture.py) against the
    analytic per-module FLOPs so the report's latency column is device
    time, not host-side module timers."""
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None
    measure_trace: bool = False
    trace_dir: str = ""               # "" = no artifact written


@dataclasses.dataclass
class CommConfig(ConfigModel):
    """Collective *scheduling* policy (TPU-native). The reference controls
    when collectives run imperatively (``overlap_comm`` /
    ``contiguous_gradients`` in ``runtime/zero/stage_1_and_2.py``); here
    GSPMD places them, and this section controls the structure the engine
    hands the compiler (deepspeed_tpu/comm/schedule.py)."""
    # accumulate microbatch grads in a per-device LOCAL (unreduced) buffer
    # inside the scan and issue ONE data-axis reduction at the step boundary
    # (DeepSpeed no_sync semantics): dp-sync collective counts become
    # independent of gradient_accumulation_steps. Costs a full-size (not
    # 1/dp) grad accumulator per device under stage 2.
    deferred_grad_sync: bool = False
    # on data x fsdp meshes, decompose the dp grad mean into an fsdp-axis
    # reduce-scatter followed by a data-axis all-reduce of the SHARDED
    # buffer: the big payload stays on the inner (fast) axis, the outer
    # axis moves 1/fsdp of the bytes
    hierarchical_grad_reduce: bool = False
    # 0 = lax.scan microbatch loop (one static collective site, compile time
    # independent of gas); K >= gas = fully unrolled microbatches (the
    # latency-hiding scheduler can overlap microbatch i's reduction with
    # microbatch i+1's compute; compile time and census scale with gas)
    microbatch_unroll: int = 0

    def validate(self):
        if self.microbatch_unroll < 0:
            raise ConfigError("comm.microbatch_unroll must be >= 0")


@dataclasses.dataclass
class CommsLoggerConfig(ConfigModel):
    """Reference: ``deepspeed/comm/config.py`` + ``utils/comms_logging.py:58``."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = config_field([])


@dataclasses.dataclass
class AIOConfig(ConfigModel):
    """Reference: aio section (``runtime/swap_tensor/constants.py``).

    The offload tiers open TWO native handles — one ring for prefetch
    reads, one for write-behind — so the read and write queues never
    serialize behind each other. ``read_queue_depth``/``write_queue_depth``
    size them independently (None = ``queue_depth`` for both)."""
    block_size: int = 1_048_576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True
    read_queue_depth: Optional[int] = None
    write_queue_depth: Optional[int] = None


@dataclasses.dataclass
class CheckpointConfig(ConfigModel):
    tag_validation: str = "Warn"      # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = config_field({})
    async_save: bool = False
    # --- integrity chain (deepspeed_tpu/robustness/integrity.py) ---
    # write a per-tag manifest + atomic COMMITTED marker; load_checkpoint
    # (tag=None) validates and walks back past torn/corrupt saves
    integrity: bool = True
    # re-hash file contents on validate (catches bitrot, not just
    # truncation); sizes are always checked
    integrity_checksums: bool = True
    # bounded retention: keep the newest K *valid* tags, prune older good
    # ones after each committed save (0 = unlimited; the tag `latest`
    # names is never pruned)
    keep_last_k: int = 0

    def validate(self):
        if self.keep_last_k < 0:
            raise ConfigError("checkpoint.keep_last_k must be >= 0")


@dataclasses.dataclass
class CurriculumParams(ConfigModel):
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = config_field({})


@dataclasses.dataclass
class CurriculumConfig(ConfigModel):
    """Reference: curriculum_learning section (``runtime/data_pipeline/curriculum_scheduler.py``)."""
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = config_field({})


@dataclasses.dataclass
class PLDConfig(ConfigModel):
    """Reference: ``runtime/progressive_layer_drop.py`` (theta/gamma keys)."""
    enabled: bool = False
    theta: float = 0.5     # keep-probability floor
    gamma: float = 0.001   # decay rate of theta(t) toward the floor


@dataclasses.dataclass
class DataEfficiencyConfig(ConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = config_field({})
    data_routing: Dict[str, Any] = config_field({})


@dataclasses.dataclass
class CompressionConfig(ConfigModel):
    """Reference: ``compression/config.py`` surface (weight/activation quant,
    pruning, layer reduction)."""
    weight_quantization: Dict[str, Any] = config_field({})
    activation_quantization: Dict[str, Any] = config_field({})
    sparse_pruning: Dict[str, Any] = config_field({})
    row_pruning: Dict[str, Any] = config_field({})
    head_pruning: Dict[str, Any] = config_field({})
    channel_pruning: Dict[str, Any] = config_field({})
    layer_reduction: Dict[str, Any] = config_field({})


@dataclasses.dataclass
class ElasticityConfig(ConfigModel):
    """Reference: ``elasticity/config.py`` (v0.1/0.2 keys)."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = config_field([2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


@dataclasses.dataclass
class FaultsConfig(ConfigModel):
    """Deterministic fault injection (deepspeed_tpu/robustness/faults.py).
    Entries fire at exact step / operation indices; `seed` feeds the
    rate-based entries so a schedule replays identically. Reference
    analogue: none — the reference's elasticity is only exercised by real
    cluster failures."""
    enabled: bool = False
    seed: int = 0
    # list of fault dicts: {"kind": "device_fault"|"io_error"|"torn_save"|
    # "corrupt_payload"|"preempt"|"step_fault"|"clock_skew"|
    # "decode_dispatch"|"pool_exhaust"|"backend_fault", ...} — see
    # robustness.FaultSchedule for the per-kind keys (the last three are
    # the serving-tier seams; `preempt` also takes a serving `round`)
    entries: List[Dict[str, Any]] = config_field([])

    def validate(self):
        if self.enabled:
            from deepspeed_tpu.robustness.faults import FaultSchedule
            try:
                FaultSchedule(self.entries, self.seed)
            except ValueError as e:  # config surface raises ConfigError
                raise ConfigError(f"robustness.faults: {e}") from e


@dataclasses.dataclass
class RobustnessConfig(ConfigModel):
    """Fault-tolerance knobs (deepspeed_tpu/robustness). Checkpoint
    integrity/retention live under the `checkpoint` section for key parity
    with the reference; this section owns what has no reference analogue."""
    faults: FaultsConfig = config_field(FaultsConfig)


@dataclasses.dataclass
class AutotuningConfig(ConfigModel):
    enabled: bool = False
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "gridsearch"     # gridsearch | random | model_based
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    max_train_batch_size: Optional[int] = None
    mp_size: int = 1
    fast: bool = True


@dataclasses.dataclass
class AnalysisConfig(ConfigModel):
    """graft-lint (``deepspeed_tpu/analysis``) knobs. TPU-native: the
    reference has no compiled program to lint; its nearest relative is the
    runtime ``comms_logger`` section. All thresholds are bytes."""
    # collectives smaller than this are control-plane sync (loss scalars,
    # overflow flags), exempt from the kind policy
    min_collective_bytes: int = 1024
    # exact census pin {op-kind: count}; any drift is an error. Empty = kind
    # policy only (see analysis/expectations.py)
    expect_collectives: Dict[str, int] = config_field({})
    min_donation_bytes: int = 1024
    min_upcast_bytes: int = 1 << 20
    min_replicated_bytes: int = 1 << 20
    max_replicated_bytes: int = 0
    # overlap audit (scheduled-HLO): max synchronous/exposed collectives the
    # compiled step may contain before the "collective-exposed" finding
    # fires. None (default) = report-only — the overlap census still lands
    # in the report/JSON, but CPU lowerings (which never emit async
    # collective pairs) don't fail the gate.
    max_exposed_collectives: Optional[int] = None
    # exposed collectives smaller than this are control-plane sync and
    # exempt from the overlap gate
    min_exposed_bytes: int = 1024
    # memory lint (scheduled-HLO liveness): statically modeled peak HBM a
    # compiled step may reach before "memory-peak" fires. None (default) =
    # report-only — peak_hbm_bytes still lands in the report/JSON with its
    # params/grads/opt/activations breakdown, but absolute budgets are
    # model- and mesh-specific so the gate is opt-in.
    max_hbm_bytes: Optional[int] = None
    # ZeRO memory law: a state class expected to shard 1/dp may exceed
    # logical/dp by this factor (unshardable small leaves, persistence
    # thresholds, padding) before "memory-law" fires, and the absolute
    # excess must also clear min_law_bytes
    memory_law_tolerance: float = 1.5
    min_law_bytes: int = 1 << 20
    # finding keys / rule ids to suppress (accepted exceptions)
    suppress: List[str] = config_field([])
    # path to a baseline JSON (analysis.report.save_baseline): known
    # findings are suppressed, recorded census becomes an exact pin
    baseline: Optional[str] = None


@dataclasses.dataclass
class TelemetryTraceConfig(ConfigModel):
    """Windowed ``jax.profiler`` capture (device-side timeline). The host
    span recorder is always on with telemetry; this section only gates the
    heavyweight profiler window."""
    enabled: bool = False
    start_step: int = 10        # first step of the capture window
    num_steps: int = 2          # window length in steps
    output_dir: str = "telemetry_traces"

    def validate(self):
        if self.num_steps < 1:
            raise ConfigError("telemetry.trace.num_steps must be >= 1")


@dataclasses.dataclass
class AnomalyConfig(ConfigModel):
    """Thresholds for the window anomaly rules (telemetry/anomaly.py)."""
    enabled: bool = True
    ema_alpha: float = 0.3            # baseline EMA weight per window
    warmup_windows: int = 1           # windows that only seed baselines
    loss_spike_factor: float = 2.0    # |loss_mean| > factor x baseline
    gnorm_drift_factor: float = 10.0  # gnorm_mean outside [base/f, base*f]
    overflow_burst_rate: float = 0.25  # overflow-skipped fraction of window
    stall_regression_factor: float = 3.0  # block ms/step > factor x baseline


@dataclasses.dataclass
class TelemetryConfig(ConfigModel):
    """TPU-native observability (``deepspeed_tpu/telemetry``): in-graph
    window accumulators in the donated jitted state, host step tracing,
    anomaly events, and the static x runtime join (modeled comms bytes/sec +
    window MFU). Design constraint: ZERO added steady-state host syncs — the
    accumulator leaf drains through the engine's existing single batched
    device_get at steps_per_print boundaries."""
    enabled: bool = False
    gnorm_hist_buckets: int = 16      # log2 buckets of the grad-norm hist
    update_ratio: bool = True         # per-step ||update||/||param|| stats
    static_join: bool = True          # census/flops x observed rate events
    jsonl_path: Optional[str] = None  # machine-readable event sink (JSONL)
    max_trace_events: int = 20000     # host span ring size
    trace: TelemetryTraceConfig = config_field(TelemetryTraceConfig)
    anomaly: AnomalyConfig = config_field(AnomalyConfig)

    def validate(self):
        if self.gnorm_hist_buckets < 2:
            raise ConfigError("telemetry.gnorm_hist_buckets must be >= 2")


@dataclasses.dataclass
class TransformerTuningConfig(ConfigModel):
    """Model-level perf levers for transformer ModelSpecs. The engine
    applies them with a ``dataclasses.replace`` + ``make_model`` rebuild
    (the act-quant idiom): the param structure is untouched, only the
    compute path changes. Non-transformer models ignore the section with a
    warning."""
    # fused attention backward block (ops/flash_attention fused_backward):
    # the delta epilogue runs inside the backward grids; removes the XLA
    # delta pass + its [B,N,S,1] HBM round-trip per layer per step
    fused_backward: bool = False
    # chunked TP collective-matmul overlap: row-parallel out-projections
    # decompose the tensor-axis reduction into this many independent psums
    # the latency-hiding scheduler can interleave with the next chunk's
    # matmul. 0/1 = off; no-op without a tensor mesh axis.
    tp_overlap_chunks: int = 0

    def validate(self):
        if self.tp_overlap_chunks < 0:
            raise ConfigError("transformer.tp_overlap_chunks must be >= 0")


@dataclasses.dataclass
class MeshConfig(ConfigModel):
    """TPU-native: explicit mesh override. By default the planner derives the
    mesh from world size and the parallelism degrees."""
    axes: Dict[str, int] = config_field({})   # e.g. {"data": 4, "tensor": 2}
    allow_split_physical_axes: bool = False


# --------------------------------------------------------------------------
# Root config
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Config(ConfigModel):
    # batch triad (reference: runtime/config.py batch reconciliation)
    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    dump_state: bool = False
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    sparse_gradients: bool = False
    gradient_clipping: float = 0.0
    communication_data_type: Optional[str] = None
    seed: int = 42
    disable_allgather: bool = False
    memory_breakdown: bool = False

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = config_field(FP16Config)
    bf16: BF16Config = config_field(BF16Config)
    zero_optimization: ZeroConfig = config_field(ZeroConfig)
    activation_checkpointing: ActivationCheckpointingConfig = config_field(ActivationCheckpointingConfig)
    pipeline: PipelineConfig = config_field(PipelineConfig)
    tensor_parallel: TensorParallelConfig = config_field(TensorParallelConfig)
    sequence_parallel: SequenceParallelConfig = config_field(SequenceParallelConfig)
    moe: MoEConfig = config_field(MoEConfig)
    mesh: MeshConfig = config_field(MeshConfig)

    tensorboard: MonitorSinkConfig = config_field(MonitorSinkConfig)
    wandb: MonitorSinkConfig = config_field(MonitorSinkConfig)
    csv_monitor: MonitorSinkConfig = config_field(MonitorSinkConfig)
    json_monitor: MonitorSinkConfig = config_field(MonitorSinkConfig)
    telemetry: TelemetryConfig = config_field(TelemetryConfig)
    flops_profiler: FlopsProfilerConfig = config_field(FlopsProfilerConfig)
    comm: CommConfig = config_field(CommConfig)
    comms_logger: CommsLoggerConfig = config_field(CommsLoggerConfig)
    aio: AIOConfig = config_field(AIOConfig)
    checkpoint: CheckpointConfig = config_field(CheckpointConfig)
    curriculum_learning: CurriculumConfig = config_field(CurriculumConfig)
    progressive_layer_drop: PLDConfig = config_field(PLDConfig)
    data_efficiency: DataEfficiencyConfig = config_field(DataEfficiencyConfig)
    compression_training: CompressionConfig = config_field(CompressionConfig)
    # MoQ (reference: runtime/quantize.py Quantizer + "quantize_training"
    # JSON section): start_bits -> target_bits over quantize_period steps,
    # optionally eigenvalue-scheduled per layer
    quantize_training: Dict[str, Any] = config_field({})
    elasticity: ElasticityConfig = config_field(ElasticityConfig)
    autotuning: AutotuningConfig = config_field(AutotuningConfig)
    analysis: AnalysisConfig = config_field(AnalysisConfig)
    robustness: RobustnessConfig = config_field(RobustnessConfig)
    transformer: TransformerTuningConfig = config_field(
        TransformerTuningConfig)

    # ---------------------------------------------------------------------
    @classmethod
    def load(cls, source) -> "Config":
        """Accept a dict, a JSON path, or an existing Config."""
        if isinstance(source, Config):
            return source
        if isinstance(source, str):
            if not os.path.exists(source):
                raise ConfigError(f"config file not found: {source}")
            with open(source) as f:
                source = json.load(f)
        return cls.from_dict(source or {})

    def validate(self):
        if self.fp16.enabled and self.bf16.enabled:
            # reference errors on fp16+bf16 both on; we prefer the explicit one
            logger.warning("config: fp16 and bf16 both enabled — using fp16 "
                           "(disable one explicitly to silence)")
            self.bf16 = BF16Config(enabled=False)
        zero = self.zero_optimization
        if zero.offload_param.enabled and zero.stage != 3:
            raise ConfigError("offload_param requires zero stage 3")

    # --- batch triad (train = micro × gas × dp_world) ---------------------
    def resolve_batch_size(self, dp_world_size: int) -> None:
        train, micro, gas = (self.train_batch_size,
                             self.train_micro_batch_size_per_gpu,
                             self.gradient_accumulation_steps)
        if train is not None and micro is not None and gas is not None:
            if train != micro * gas * dp_world_size:
                raise ConfigError(
                    f"batch mismatch: train_batch_size={train} != "
                    f"micro({micro}) * gas({gas}) * dp({dp_world_size})")
        elif train is not None and micro is not None:
            gas = train // (micro * dp_world_size)
        elif train is not None and gas is not None:
            micro = train // (gas * dp_world_size)
        elif micro is not None and gas is not None:
            train = micro * gas * dp_world_size
        elif train is not None:
            gas = 1
            micro = train // dp_world_size
        elif micro is not None:
            gas = 1
            train = micro * dp_world_size
        else:
            micro, gas = 1, 1
            train = dp_world_size
        if micro is None or micro <= 0 or gas is None or gas <= 0:
            raise ConfigError(
                f"cannot solve batch triad: train={train} micro={micro} gas={gas} dp={dp_world_size}")
        if train != micro * gas * dp_world_size:
            raise ConfigError(
                f"batch triad unsolvable: train_batch_size={train} not divisible into "
                f"micro({micro}) * gas({gas}) * dp({dp_world_size})")
        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

    # --- dtype helpers ----------------------------------------------------
    @property
    def compute_dtype(self):
        import jax.numpy as jnp
        if self.fp16.enabled:
            return jnp.float16
        if self.bf16.enabled:
            return jnp.bfloat16
        return jnp.float32

    @property
    def loss_scale_enabled(self) -> bool:
        return self.fp16.enabled
