"""Ring attention: sequence/context parallelism over the `seq` mesh axis.

The reference has NO sequence parallelism at this snapshot (SURVEY §2.7 —
long sequences are handled only by block-sparse kernels + activation
partitioning), but it is a first-class target for the TPU build: activations
are sharded along the sequence dim, and attention exchanges K/V shards around
the ring with `lax.ppermute` while accumulating online-softmax partials —
K/V transfer overlaps with the current block's compute (XLA schedules the
collective-permute concurrently), so attention scales to sequences that
don't fit one chip's HBM.

Causality across shards is handled at block granularity: a K/V shard wholly
in the future contributes nothing (its contribution is masked), the diagonal
shard applies the intra-block triangular mask, and wholly-past shards are
unmasked.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_off, k_off, causal, sm_scale):
    """One q-shard vs one k/v-shard with global-position causal masking.
    q: [B, Sq, N, D], k/v: [B, Sk, N, D]. Returns (scores_max m [B,N,Sq,1],
    exp-sum l [B,N,Sq,1], weighted acc [B,Sq,N,D]) partials."""
    B, Sq, N, D = q.shape
    Sk = k.shape[1]
    s = jnp.einsum("bsnd,btnd->bnst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                      # [B,N,Sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bnst,btnd->bsnd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention_local(q, k, v, *, axis_name: str = "seq",
                         causal: bool = True,
                         sm_scale: Optional[float] = None,
                         axis_size: Optional[int] = None):
    """Call INSIDE shard_map: q/k/v are the local sequence shards
    [B, S_local, N, D]; returns the local output shard. ``axis_size``
    is the static ring size — pass it on jax versions without
    ``lax.axis_size`` (the ppermute table must be built from a Python
    int either way)."""
    B, Sl, N, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    sp = axis_size if axis_size is not None else lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    q_off = my * Sl
    # send k/v to the NEXT rank each step => at step t we hold shard (my - t)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    m = jnp.full((B, N, Sl, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, N, Sl, 1), jnp.float32)
    acc = jnp.zeros((B, Sl, N, D), jnp.float32)

    def step(t, carry):
        m, l, acc, k_cur, v_cur = carry
        kv_idx = (my - t) % sp
        k_off = kv_idx * Sl
        bm, bl, bacc = _block_attend(q, k_cur, v_cur, q_off, k_off, causal,
                                     sm_scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)          # rescale old
        beta = jnp.exp(bm - m_new)          # rescale incoming block
        l_new = l * alpha + bl * beta
        acc_new = acc * jnp.moveaxis(alpha, 1, 2) + \
            bacc * jnp.moveaxis(beta, 1, 2)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt

    m, l, acc, _, _ = lax.fori_loop(0, sp, step, (m, l, acc, k, v))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / jnp.moveaxis(l_safe, 1, 2)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "seq",
                   causal: bool = True, sm_scale: Optional[float] = None,
                   batch_axes=("data", "fsdp", "expert"),
                   heads_axis: str = "tensor"):
    """SPMD entry: q/k/v are GLOBAL [B, S, N, D] arrays; full-manual
    shard_map (this jax version's partial-auto mode rejects sharded auto
    axes): batch over dp axes, sequence over `axis_name`, heads over
    `tensor` (TP attention layout), head_dim replicated. Requires pipe=1
    (ring attention inside a pipelined stage would need nested manual
    meshes)."""
    spec = P(batch_axes, axis_name, heads_axis, None)
    local = functools.partial(ring_attention_local, axis_name=axis_name,
                              causal=causal, sm_scale=sm_scale,
                              axis_size=int(mesh.shape[axis_name]))
    try:
        fn = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False)
    except (AttributeError, TypeError):
        # older jax: jax.shard_map / check_vma don't exist yet — the
        # experimental spelling with check_rep is the same full-manual mode
        from jax.experimental.shard_map import shard_map
        fn = shard_map(local, mesh,
                       in_specs=(spec, spec, spec), out_specs=spec,
                       check_rep=False)
    return fn(q, k, v)
