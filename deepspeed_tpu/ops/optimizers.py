"""Optimizer core: a functional, sharding-transparent optimizer API.

Reference equivalents: ``deepspeed/ops/adam/fused_adam.py:16`` (FusedAdam),
``csrc/adam/multi_tensor_adam.cu`` (multi-tensor apply), ``runtime/fp16``
master-weight optimizers. TPU-native design: an optimizer is a pair of pure
functions over pytrees (optax's GradientTransformation protocol, so optax
optimizers drop in too). "Fused" and "multi-tensor" are XLA's job — a jitted
update over the whole pytree compiles to fused HBM-bandwidth-bound loops, which
is exactly what multi_tensor_apply hand-builds on CUDA. A Pallas fused kernel
variant lives in ops/fused_kernels.py for the largest flat params.

Master weights: when params are bf16/fp16, state carries an fp32 copy
(reference: fp16/fused_optimizer.py, bf16_optimizer.py). The fp32 master is
sharded identically to the param (ZeRO-1 shards it over dp via the engine's
state sharding rules).
"""

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]
ScalarOrSchedule = Union[float, Schedule]


class Optimizer(NamedTuple):
    """optax-compatible: init(params) -> state; update(grads, state, params)
    -> (new_params_updates_applied, state). Unlike optax we return the new
    params directly (master-weight handling makes 'updates' ambiguous)."""
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Any]


def _lr_at(lr: ScalarOrSchedule, step):
    return lr(step) if callable(lr) else jnp.asarray(lr, jnp.float32)


def from_optax(tx) -> Optimizer:
    """Adapt an optax GradientTransformation to this framework's Optimizer
    protocol (optax returns (updates, state); we return (new_params, state)).
    The optax state is wrapped in a dict so the engine's sharding logic can
    walk it uniformly."""

    def init(params):
        return {"optax": tx.init(params)}

    def update(grads, state, params):
        import optax
        updates, new_inner = tx.update(grads, state["optax"], params)
        new_params = optax.apply_updates(params, updates)
        return new_params, {"optax": new_inner}

    return Optimizer(init, update)


def is_optax_transform(opt) -> bool:
    try:
        import optax
        return isinstance(opt, optax.GradientTransformation) and \
            not isinstance(opt, Optimizer)
    except ImportError:
        return False


def cast_tree(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def _master_init(params, use_master: bool):
    if not use_master:
        return None
    return jax.tree.map(lambda p: p.astype(jnp.float32), params)


def _resolve_master(params, master):
    """fp32 view of params for the update."""
    if master is not None:
        return master
    return jax.tree.map(lambda p: p.astype(jnp.float32), params)


def _writeback(new_master, params, master):
    """Cast updated fp32 master back to the param dtype."""
    new_params = jax.tree.map(lambda m, p: m.astype(p.dtype), new_master, params)
    return new_params, (new_master if master is not None else None)


def sgd(lr: ScalarOrSchedule = 1e-2, momentum: float = 0.0,
        weight_decay: float = 0.0, nesterov: bool = False,
        use_master_weights: bool = True) -> Optimizer:
    def init(params):
        state = {"step": jnp.zeros((1,), jnp.int32)}
        if momentum:
            state["momentum"] = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        state["master"] = _master_init(params, use_master_weights)
        return state

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = _lr_at(lr, step)
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        if weight_decay:
            g32 = jax.tree.map(lambda g, p: g + weight_decay * p, g32, master)
        if momentum:
            buf = jax.tree.map(lambda b, g: momentum * b + g, state["momentum"], g32)
            upd = jax.tree.map(lambda b, g: g + momentum * b, buf, g32) if nesterov else buf
        else:
            buf, upd = None, g32
        new_master = jax.tree.map(lambda m, u: m - lr_t * u, master, upd)
        new_params, new_master = _writeback(new_master, params, state.get("master"))
        new_state = {"step": step, "master": new_master}
        if momentum:
            new_state["momentum"] = buf
        return new_params, new_state

    return Optimizer(init, update)


def adagrad(lr: ScalarOrSchedule = 1e-2, eps: float = 1e-10,
            weight_decay: float = 0.0, initial_accumulator: float = 0.0,
            use_master_weights: bool = True) -> Optimizer:
    """Reference: ``csrc/adagrad/cpu_adagrad.cpp`` / ``ops/adagrad``. """
    def init(params):
        return {
            "step": jnp.zeros((1,), jnp.int32),
            "accum": jax.tree.map(
                lambda p: jnp.full(p.shape, initial_accumulator, jnp.float32), params),
            "master": _master_init(params, use_master_weights),
        }

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = _lr_at(lr, step)
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        if weight_decay:
            g32 = jax.tree.map(lambda g, p: g + weight_decay * p, g32, master)
        accum = jax.tree.map(lambda a, g: a + g * g, state["accum"], g32)
        new_master = jax.tree.map(
            lambda m, g, a: m - lr_t * g / (jnp.sqrt(a) + eps), master, g32, accum)
        new_params, new_master = _writeback(new_master, params, state.get("master"))
        return new_params, {"step": step, "accum": accum, "master": new_master}

    return Optimizer(init, update)


def lion(lr: ScalarOrSchedule = 1e-4, beta1: float = 0.9, beta2: float = 0.99,
         weight_decay: float = 0.0, use_master_weights: bool = True) -> Optimizer:
    """Lion (sign-momentum) — no reference equivalent; included because its
    1-bit update is a natural fit for compressed DCN gradients."""
    def init(params):
        return {
            "step": jnp.zeros((1,), jnp.int32),
            "mu": jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "master": _master_init(params, use_master_weights),
        }

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = _lr_at(lr, step)
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        upd = jax.tree.map(lambda m, g: jnp.sign(beta1 * m + (1 - beta1) * g),
                           state["mu"], g32)
        mu = jax.tree.map(lambda m, g: beta2 * m + (1 - beta2) * g, state["mu"], g32)
        new_master = jax.tree.map(
            lambda p, u: p - lr_t * (u + weight_decay * p), master, upd)
        new_params, new_master = _writeback(new_master, params, state.get("master"))
        return new_params, {"step": step, "mu": mu, "master": new_master}

    return Optimizer(init, update)


def chain_clip_by_global_norm(optimizer: Optimizer, max_norm: float) -> Optimizer:
    """Global-norm clipping before the update (reference:
    ``runtime/utils.py`` global-norm helpers + engine gradient_clipping)."""
    if not max_norm or max_norm <= 0:
        return optimizer

    def update(grads, state, params):
        g32 = cast_tree(grads, jnp.float32)
        leaves = jax.tree.leaves(g32)
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
        clipped = jax.tree.map(lambda g: g * scale, g32)
        return optimizer.update(clipped, state, params)

    return Optimizer(optimizer.init, update)


def global_grad_norm(grads) -> jnp.ndarray:
    leaves = jax.tree.leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
