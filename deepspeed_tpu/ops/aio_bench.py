"""NVMe AIO parameter sweep (``dstpu_aio_bench``).

Reference: ``csrc/aio/py_test/aio_bench_perf_sweep.py`` — sweep block size x
queue depth x thread count over O_DIRECT reads/writes and report the best
configuration to feed ``aio`` config keys (here: the AIOHandle constructor
args used by runtime/swap_tensor.py and runtime/infinity.py).
"""

import argparse
import json
import os
import sys
import tempfile
import time
from typing import List

import numpy as np


def _bench_one(handle, path: str, arr: np.ndarray, iters: int,
               direct: bool) -> dict:
    t0 = time.perf_counter()
    for _ in range(iters):
        handle.pwrite(path, arr, direct=direct)
    wt = (time.perf_counter() - t0) / iters
    out = np.empty_like(arr)
    t0 = time.perf_counter()
    for _ in range(iters):
        handle.pread(path, arr.shape, arr.dtype, direct=direct, out=out)
    rt = (time.perf_counter() - t0) / iters
    gb = arr.nbytes / 1e9
    return {"write_gbps": round(gb / wt, 3), "read_gbps": round(gb / rt, 3)}


def sweep(path: str, file_mb: int = 256, iters: int = 3,
          block_sizes: List[int] = (1 << 18, 1 << 20, 1 << 22),
          queue_depths: List[int] = (4, 16, 32, 64),
          thread_counts: List[int] = (1, 4, 8),
          direct: bool = True) -> List[dict]:
    from deepspeed_tpu.ops.aio import AIOHandle, aio_available
    if not aio_available():
        raise RuntimeError("native aio library unavailable")
    arr = np.random.default_rng(0).integers(
        0, 255, file_mb * (1 << 20), dtype=np.uint8)
    results = []
    fname = os.path.join(path, "dstpu_aio_bench.bin")
    for bs in block_sizes:
        for qd in queue_depths:
            for tc in thread_counts:
                h = AIOHandle(block_size=bs, queue_depth=qd, thread_count=tc)
                uring = h.uses_io_uring   # before the bench: a failed run
                try:                      # can leave the handle unreadable
                    r = _bench_one(h, fname, arr, iters, direct)
                except Exception as e:  # noqa: BLE001 — record and continue
                    r = {"error": str(e)}
                finally:
                    h.close()
                r.update({"block_size": bs, "queue_depth": qd,
                          "thread_count": tc, "io_uring": uring})
                results.append(r)
    try:
        os.unlink(fname)
    except OSError:
        pass
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="dstpu_aio_bench",
        description="NVMe AIO block-size/queue-depth/thread sweep")
    p.add_argument("--path", default=None,
                   help="directory on the target disk (default: tmpdir)")
    p.add_argument("--file-mb", type=int, default=256)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--no-direct", dest="direct", action="store_false",
                   help="buffered IO (default is O_DIRECT: without it the "
                        "sweep measures the page cache, not the device)")
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)
    path = args.path or tempfile.mkdtemp(prefix="dstpu-aio-")
    rows = sweep(path, file_mb=args.file_mb, iters=args.iters,
                 direct=args.direct)
    ok = [r for r in rows if "error" not in r]
    if args.json:
        print(json.dumps(rows))
    else:
        print(f"{'block':>10} {'depth':>6} {'threads':>8} "
              f"{'write GB/s':>11} {'read GB/s':>10}")
        for r in rows:
            if "error" in r:
                print(f"{r['block_size']:>10} {r['queue_depth']:>6} "
                      f"{r['thread_count']:>8}  ERROR {r['error']}")
            else:
                print(f"{r['block_size']:>10} {r['queue_depth']:>6} "
                      f"{r['thread_count']:>8} {r['write_gbps']:>11} "
                      f"{r['read_gbps']:>10}")
        if ok:
            best = max(ok, key=lambda r: r["read_gbps"] + r["write_gbps"])
            print(f"\nbest: block_size={best['block_size']} "
                  f"queue_depth={best['queue_depth']} "
                  f"thread_count={best['thread_count']} "
                  f"(read {best['read_gbps']} GB/s, "
                  f"write {best['write_gbps']} GB/s)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
