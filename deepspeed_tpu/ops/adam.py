"""Adam family.

Reference: ``deepspeed/ops/adam/fused_adam.py:16`` (FusedAdam, adam_w_mode),
``cpu_adam.py:12`` (DeepSpeedCPUAdam), ``runtime/fp16/onebit/adam.py:11``
(OnebitAdam: dense warmup -> compressed stage with error feedback),
``onebit/zoadam.py:11`` (0/1 Adam: variance freeze + local steps).

All are pure pytree transforms; XLA fuses the elementwise chains into
bandwidth-bound loops (the CUDA multi_tensor_apply equivalent). The fp32
master/moment states follow the param sharding rules, so under ZeRO-1+ they
are automatically partitioned across the dp axis.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizers import (
    Optimizer, ScalarOrSchedule, _lr_at, _master_init, _resolve_master,
    _writeback, cast_tree,
)


def fused_adam_update(master, m, v, g, lr_t, step, *, b1, b2, eps,
                      wd, awm, bc):
    """The one flat AdamW core shared by every host/device offload variant
    (reference: the Step kernel of ``csrc/adam/cpu_adam.cpp`` /
    ``fused_adam.py``). ``g`` arrives already scaled (clip/loss-scale/gas
    folded in by the caller); returns (master', m', v')."""
    if wd and not awm:
        g = g + wd * master
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    if bc:
        c1 = 1 - b1 ** step.astype(jnp.float32)
        c2 = 1 - b2 ** step.astype(jnp.float32)
    else:
        c1 = c2 = jnp.float32(1.0)
    upd = (m / c1) / (jnp.sqrt(v / c2) + eps)
    if awm and wd:
        upd = upd + wd * master
    return master - lr_t * upd, m, v


def adam_tree_update(opt, grads, lr_t, step, coef, *, b1, b2, eps, wd,
                     awm, bc, out_dtype):
    """AdamW over a {master, m, v}-leaf tree: returns (new_opt tree,
    new params tree cast to ``out_dtype``). The shared wrapper for every
    host-offload flavor that keeps its state as a pytree (the
    layer-streamed executor's embed/head update, XlaHostAdamSwapper)."""
    is_opt = lambda x: isinstance(x, dict) and "master" in x  # noqa: E731

    def upd(o, g):
        master, m, v = fused_adam_update(
            o["master"], o["m"], o["v"], g.astype(jnp.float32) * coef,
            lr_t, step, b1=b1, b2=b2, eps=eps, wd=wd, awm=awm, bc=bc)
        return {"master": master, "m": m, "v": v}

    new_opt = jax.tree.map(upd, opt, grads, is_leaf=is_opt)
    new_params = jax.tree.map(lambda o: o["master"].astype(out_dtype),
                              new_opt, is_leaf=is_opt)
    return new_opt, new_params


def adam(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
         weight_decay: float = 0.0, adam_w_mode: bool = False,
         bias_correction: bool = True, use_master_weights: bool = True,
         amsgrad: bool = False) -> Optimizer:
    """Adam / AdamW (adam_w_mode=True -> decoupled decay; reference
    ``fused_adam.py`` has the same flag)."""
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        state = {
            "step": jnp.zeros((1,), jnp.int32),
            "exp_avg": jax.tree.map(zeros, params),
            "exp_avg_sq": jax.tree.map(zeros, params),
            "master": _master_init(params, use_master_weights),
        }
        if amsgrad:
            state["max_exp_avg_sq"] = jax.tree.map(zeros, params)
        return state

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = _lr_at(lr, step)
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        if weight_decay and not adam_w_mode:
            g32 = jax.tree.map(lambda g, p: g + weight_decay * p, g32, master)
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["exp_avg"], g32)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["exp_avg_sq"], g32)
        if amsgrad:
            vmax = jax.tree.map(jnp.maximum, state["max_exp_avg_sq"], v)
            v_used = vmax
        else:
            vmax, v_used = None, v
        if bias_correction:
            c1 = 1 - b1 ** step.astype(jnp.float32)
            c2 = 1 - b2 ** step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.float32(1.0)

        def step_fn(p, m_, v_):
            denom = jnp.sqrt(v_ / c2) + eps
            upd = (m_ / c1) / denom
            if adam_w_mode and weight_decay:
                upd = upd + weight_decay * p
            return p - lr_t * upd

        new_master = jax.tree.map(step_fn, master, m, v_used)
        new_params, new_master = _writeback(new_master, params, state.get("master"))
        new_state = {"step": step, "exp_avg": m, "exp_avg_sq": v, "master": new_master}
        if amsgrad:
            new_state["max_exp_avg_sq"] = vmax
        return new_params, new_state

    return Optimizer(init, update)


def adamw(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
          weight_decay: float = 0.01, **kw) -> Optimizer:
    return adam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                adam_w_mode=True, **kw)


# onebit_adam moved to deepspeed_tpu.ops.onebit (phased implementation with
# a real compressed collective); re-exported here for backward compatibility.
from deepspeed_tpu.ops.onebit import onebit_adam  # noqa: E402,F401
