"""Native host-side fused Adagrad (ctypes binding).

Reference: ``deepspeed/ops/adagrad/cpu_adagrad.py:12`` (DeepSpeedCPUAdagrad)
over ``csrc/adagrad/cpu_adagrad.cpp`` — the Adagrad member of the
ZeRO-Offload host-optimizer family. Same build/binding pattern as
``ops/cpu_adam.py``; CPUAdagrad exposes the CPUAdam step interface (step_num
accepted and ignored — Adagrad has no bias correction) so the host
swap tiers can treat the two interchangeably.
"""

import ctypes
import hashlib
import math
import os
import subprocess
from typing import Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "adagrad", "dstpu_cpu_adagrad.cpp")

_LIB = None


def _cache_dir() -> str:
    base = os.environ.get("DSTPU_CACHE_DIR") or os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "deepspeed_tpu")
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_cache_dir(), f"libdstpu_cpu_adagrad-{digest}.so")
    if os.path.exists(so):
        return so
    tmp = f"{so}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except Exception as e:  # pragma: no cover - toolchain missing
        logger.warning(f"cpu_adagrad build failed: {e}")
        return None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    so = _build()
    if so is None:
        return None
    lib = ctypes.CDLL(so)
    f32p = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    lib.dstpu_adagrad_step_bf16.argtypes = [
        f32p, f32p, u16p, u16p, ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float]
    lib.dstpu_adagrad_step_f32.argtypes = [
        f32p, f32p, f32p, f32p, ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float]
    _LIB = lib
    return lib


def cpu_adagrad_available() -> bool:
    return _load() is not None


def adagrad_step_flat(master: np.ndarray, accum: np.ndarray,
                      grads: np.ndarray, *, lr: float, eps: float = 1e-10,
                      weight_decay: float = 0.0, grad_scale: float = 1.0,
                      out: Optional[np.ndarray] = None):
    """One fused Adagrad step over caller-owned flat fp32 state buffers
    (updated in place). grads: float32, or bf16 bits as uint16; ``out``
    optionally receives the updated params (uint16 bf16 bits for bf16
    grads, float32 otherwise)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native cpu_adagrad library unavailable")
    g = np.ascontiguousarray(grads).reshape(-1)
    n = g.size
    for name, arr in (("master", master), ("accum", accum)):
        if arr.size != n or arr.dtype != np.float32 \
                or not arr.flags.c_contiguous:
            raise ValueError(
                f"{name}: need contiguous float32[{n}], got "
                f"{arr.dtype}[{arr.size}]"
                f"{'' if arr.flags.c_contiguous else ' (non-contiguous)'}")
    if out is not None:
        want = np.uint16 if g.dtype == np.uint16 else np.float32
        if out.size != n or out.dtype != want \
                or not out.flags.c_contiguous:
            raise ValueError(f"out: need contiguous {np.dtype(want).name}"
                             f"[{n}], got {out.dtype}[{out.size}]")
    f32p = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)

    def p(arr, ct):
        return arr.ctypes.data_as(ctypes.POINTER(ct))

    if g.dtype == np.uint16:
        lib.dstpu_adagrad_step_bf16(
            p(master, ctypes.c_float), p(accum, ctypes.c_float),
            p(g, ctypes.c_uint16),
            p(out, ctypes.c_uint16) if out is not None
            else ctypes.cast(None, u16p),
            n, float(lr), eps, weight_decay, float(grad_scale))
    else:
        g = g.astype(np.float32, copy=False)
        lib.dstpu_adagrad_step_f32(
            p(master, ctypes.c_float), p(accum, ctypes.c_float),
            p(g, ctypes.c_float),
            p(out, ctypes.c_float) if out is not None
            else ctypes.cast(None, f32p),
            n, float(lr), eps, weight_decay, float(grad_scale))


class CPUAdagrad:
    """Fused host Adagrad over flat fp32 state buffers (master, accum).
    CPUAdam-compatible step interface (step_num ignored: no bias
    correction), so the host swap tiers can substitute it for CPUAdam."""

    def __init__(self, n: int, lr=1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, **_ignored):
        lib = _load()
        if lib is None:
            raise RuntimeError("native cpu_adagrad library unavailable "
                               "(g++ build failed)")
        self._lib = lib
        self.n = int(n)
        self.lr = lr
        self.eps = eps
        self.wd = weight_decay
        self.master = np.zeros(self.n, np.float32)
        self.accum = np.zeros(self.n, np.float32)

    def load_master(self, params: np.ndarray):
        np.copyto(self.master, np.asarray(params, np.float32).reshape(-1))

    def sq_norm(self, grads: np.ndarray) -> float:
        # reuse the Adam lib's norm kernels (identical math, built already)
        from deepspeed_tpu.ops.cpu_adam import CPUAdam  # noqa: F401
        from deepspeed_tpu.ops import cpu_adam as _ca
        lib = _ca._load()
        if lib is None:
            # the adagrad .so can build while the adam .so fails — same
            # RuntimeError as the step path, not an AttributeError mid-swap
            raise RuntimeError("native cpu_adam library unavailable "
                               "(needed for the sq_norm kernels)")
        g = np.ascontiguousarray(grads).reshape(-1)
        if g.dtype == np.uint16:
            return float(lib.dstpu_sq_norm_bf16(
                g.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), g.size))
        g = g.astype(np.float32, copy=False)
        return float(lib.dstpu_sq_norm_f32(
            g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), g.size))

    def step(self, grads: np.ndarray, step_num: int,
             lr: Optional[float] = None, grad_scale: float = 1.0,
             out: Optional[np.ndarray] = None):
        g = np.ascontiguousarray(grads).reshape(-1)
        if out is None:
            out = np.empty(self.n,
                           np.uint16 if g.dtype == np.uint16 else np.float32)
        adagrad_step_flat(self.master, self.accum, g,
                          lr=float(self.lr if lr is None else lr),
                          eps=self.eps, weight_decay=self.wd,
                          grad_scale=grad_scale, out=out)
        return out

    def clip_coef(self, sq_total: float, clip: float,
                  grad_scale: float = 1.0) -> float:
        gnorm = math.sqrt(sq_total) * grad_scale
        if clip and clip > 0 and gnorm > clip:
            return clip / (gnorm + 1e-6)
        return 1.0
