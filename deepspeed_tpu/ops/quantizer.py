"""Quantization kernels: int8/int4, symmetric/asymmetric, grouped.

Reference: ``csrc/quantization/{quantize.cu,quant_reduce.cu,dequantize.cu}``
+ ``deepspeed/ops/quantizer`` (ds_quantizer) — CUDA kernels computing
per-group scales/offsets and packing int4 pairs.

TPU-native: the quantize/dequantize math is pure jnp (XLA fuses it into the
surrounding program — on TPU these are VPU elementwise passes); int4 values
pack two-per-uint8 with shift/mask ops. Grouping reshapes the trailing dim
into [groups, group_size] so scales broadcast — the same layout the
reference's group-wise kernels use.
"""

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["QuantizedTensor", "quantize", "dequantize", "pack_int4",
           "unpack_int4", "fake_quant", "quantize_tree", "dequantize_tree",
           "quantize_rows", "quantize_channels", "weight_matmul"]


def quantize_rows(x):
    """Per-row symmetric int8 for KV-cache storage: x [..., T, D] float ->
    (int8 [..., T, D], f32 scale [..., T]).

    The scale factors OUT of the head-dim contraction, so decode attention
    consumes the int8 bytes directly (scores = int8-dot * q_scale * k_scale)
    instead of materializing a dequantized copy — the "dequant fused into
    the attention read" contract both the contiguous ring cache and the
    paged block pool rely on (reference: the int8 inference kernel path,
    ``csrc/transformer/inference``; here the fusion is the XLA program
    itself). Shared by ``models/transformer._quant_kv`` and the serving
    tier's block writes."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127
                 ).astype(jnp.int8)
    return q, scale


def quantize_channels(w):
    """Per-OUT-CHANNEL symmetric int8 for weight storage: w [..., In, Out]
    float -> (int8 [..., In, Out], f32 scale [..., 1, Out]).

    The weight-side twin of ``quantize_rows``: the scale lives on the
    output column, so it factors OUT of the In-contraction and a matmul
    against the int8 payload finishes with one row-broadcast multiply —
    ``weight_matmul`` below. Leading dims (layer stack, expert stack)
    each get their own scales, matching ``models/transformer
    .quantize_layer_stack``'s {"q", "scale"} layout."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)   # per (.., out)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def weight_matmul(x, w, scale=None):
    """x @ w with dequant fused into the matmul EPILOGUE.

    ``w`` int8 [In, Out] (+ broadcastable per-out-channel ``scale``): the
    contraction runs against the int8 payload — the elementwise convert
    fuses into the matmul's weight read, so no dequantized copy of the
    weight ever materializes in HBM (weights stay int8 at rest, the
    weight_bits=8 serving contract) — and the f32 scale multiplies the
    [..., Out] RESULT rows (per-column scales factor out of the In
    contraction exactly). A plain float ``w`` (scale=None) is the
    ordinary matmul, so call sites stay branch-free."""
    if scale is None:
        return x @ w.astype(x.dtype)
    y = x @ w.astype(x.dtype)
    return y * jnp.reshape(scale, scale.shape[-1:]).astype(x.dtype)


@dataclasses.dataclass
class QuantizedTensor:
    """Storage container: quantized payload + per-group scale/offset."""
    q: jnp.ndarray            # int8 payload (int4: packed 2/uint8)
    scale: jnp.ndarray        # f32 [groups broadcastable]
    zero: Optional[jnp.ndarray]  # None for symmetric
    bits: int
    shape: Tuple[int, ...]    # original shape
    dtype: str = "bfloat16"   # dequantized dtype

    def tree_flatten(self):
        return ((self.q, self.scale, self.zero),
                (self.bits, self.shape, self.dtype))

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale, zero = children
        bits, shape, dtype = aux
        return cls(q=q, scale=scale, zero=zero, bits=bits, shape=shape,
                   dtype=dtype)


jax.tree_util.register_pytree_node(
    QuantizedTensor, QuantizedTensor.tree_flatten,
    QuantizedTensor.tree_unflatten)


def _grouped(x, num_groups: int):
    flat = x.reshape(-1)
    n = flat.shape[0]
    if n % num_groups:
        raise ValueError(f"size {n} not divisible by num_groups {num_groups}")
    return flat.reshape(num_groups, n // num_groups)


def quantize(x, bits: int = 8, symmetric: bool = True,
             num_groups: int = 1) -> QuantizedTensor:
    """Quantize to int{4,8} with per-group scale (and offset if asymmetric)."""
    if bits not in (4, 8):
        raise ValueError("bits must be 4 or 8")
    orig_shape = tuple(x.shape)
    g = _grouped(x.astype(jnp.float32), num_groups)
    qmax = 2 ** (bits - 1) - 1          # 127 / 7
    qmin = -(2 ** (bits - 1))           # -128 / -8
    if symmetric:
        amax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
        scale = jnp.maximum(amax / qmax, 1e-12)
        q = jnp.clip(jnp.round(g / scale), qmin, qmax).astype(jnp.int8)
        zero = None
    else:
        lo = jnp.min(g, axis=1, keepdims=True)
        hi = jnp.max(g, axis=1, keepdims=True)
        scale = jnp.maximum((hi - lo) / (2 ** bits - 1), 1e-12)
        zero = jnp.round(-lo / scale) + qmin
        q = jnp.clip(jnp.round(g / scale) + zero, qmin, qmax).astype(jnp.int8)
    if bits == 4:
        q = pack_int4(q)
    return QuantizedTensor(q=q, scale=scale, zero=zero, bits=bits,
                           shape=orig_shape, dtype=str(x.dtype))


def dequantize(qt: QuantizedTensor):
    q = qt.q
    if qt.bits == 4:
        q = unpack_int4(q)
    g = q.astype(jnp.float32)
    if qt.zero is not None:
        g = g - qt.zero
    out = (g * qt.scale).reshape(qt.shape)
    return out.astype(jnp.dtype(qt.dtype))


def pack_int4(q):
    """[G, N] int8 in [-8, 7] -> [G, N/2] uint8 (two nibbles)."""
    G, N = q.shape
    if N % 2:
        raise ValueError("int4 packing needs an even group size")
    u = (q.astype(jnp.int32) & 0xF).astype(jnp.uint8)
    return (u[:, 0::2] | (u[:, 1::2] << 4)).astype(jnp.uint8)


def unpack_int4(p):
    lo = (p & 0xF).astype(jnp.int8)
    hi = ((p >> 4) & 0xF).astype(jnp.int8)
    # sign-extend the nibble
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    G, M = p.shape
    out = jnp.stack([lo, hi], axis=2).reshape(G, 2 * M)
    return out


def fake_quant(x, bits: int = 8, symmetric: bool = True, num_groups: int = 1):
    """Straight-through-estimator quantize-dequantize (QAT forward).
    Gradient passes through unchanged (reference: compression/basic_layer.py
    QuantAct / LinearLayer_Compress weight fake-quant)."""
    qt = quantize(x, bits=bits, symmetric=symmetric, num_groups=num_groups)
    xq = dequantize(qt).astype(x.dtype)
    return x + jax.lax.stop_gradient(xq - x)


def _is_weight(path_leaf, min_ndim=2):
    return hasattr(path_leaf, "ndim") and path_leaf.ndim >= min_ndim


def quantize_tree(params, bits: int = 8, symmetric: bool = True,
                  group_size: int = 128, min_size: int = 4096):
    """Quantize every matmul-sized leaf of a param tree for storage;
    small params (norms, biases) stay in full precision — mirrors the
    reference's weight-quantization module scoping."""
    def one(x):
        if not hasattr(x, "size") or x.size < min_size or x.ndim < 2:
            return x
        n = x.size
        groups = max(1, n // group_size)
        while n % groups:
            groups -= 1
        return quantize(x, bits=bits, symmetric=symmetric, num_groups=groups)
    return jax.tree.map(one, params)


def dequantize_tree(params):
    return jax.tree.map(
        lambda x: dequantize(x) if isinstance(x, QuantizedTensor) else x,
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))
