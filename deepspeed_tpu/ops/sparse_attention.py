"""Block-sparse attention: sparsity layouts + Pallas kernel.

Reference: ``deepspeed/ops/sparse_attention/sparse_self_attention.py:11``
(SparseSelfAttention over Triton block-sparse matmul/softmax) and
``sparsity_config.py:94-545`` (Dense/Fixed/BigBird/BSLongformer/Variable
layout builders).

TPU-native re-design: the Triton path multiplies against a block mask; here
each q-block carries an explicit index list of its active k-blocks (built
host-side from the layout, padded to the max row degree), and the Pallas
kernel loops ONLY over that list with online softmax — compute and HBM
traffic scale with the layout's density, not S^2. Backward reuses the flash
decomposition with the transposed adjacency for dK/dV.

Layouts are per-head-shared (the reference's `different_layout_per_head`
defaults off for these modes); causal masking composes with any layout.
"""

import dataclasses
import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# --------------------------------------------------------------------------
# sparsity configs (reference: sparsity_config.py)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SparsityConfig:
    """Base: dense layout (reference: DenseSparsityConfig)."""
    block: int = 128

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        return np.ones((n, n), bool)


@dataclasses.dataclass(frozen=True)
class DenseSparsityConfig(SparsityConfig):
    pass


@dataclasses.dataclass(frozen=True)
class FixedSparsityConfig(SparsityConfig):
    """Local blocks + periodic global columns (reference:
    FixedSparsityConfig — num_local_blocks window, num_global_blocks stride
    summaries, 'unidirectional'/'bidirectional' attention)."""
    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        L = np.zeros((n, n), bool)
        nl = self.num_local_blocks
        for i in range(n):
            w0 = (i // nl) * nl
            L[i, w0:min(w0 + nl, n)] = True          # local window
        for w0 in range(0, n, nl):                    # global columns: the
            g = min(self.num_global_blocks, n - w0)   # first blocks of each
            L[:, w0:w0 + g] = True                    # local window
        return L


@dataclasses.dataclass(frozen=True)
class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global blocks (reference:
    BigBirdSparsityConfig)."""
    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        L = np.zeros((n, n), bool)
        w = self.num_sliding_window_blocks // 2
        for i in range(n):
            L[i, max(0, i - w):min(n, i + w + 1)] = True
        g = min(self.num_global_blocks, n)
        L[:, :g] = True
        L[:g, :] = True
        rng = np.random.default_rng(self.seed)
        for i in range(n):
            pick = rng.choice(n, size=min(self.num_random_blocks, n),
                              replace=False)
            L[i, pick] = True
        return L


@dataclasses.dataclass(frozen=True)
class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + designated global block indices (reference:
    BSLongformerSparsityConfig)."""
    num_sliding_window_blocks: int = 3
    global_block_indices: Tuple[int, ...] = (0,)

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        L = np.zeros((n, n), bool)
        w = self.num_sliding_window_blocks // 2
        for i in range(n):
            L[i, max(0, i - w):min(n, i + w + 1)] = True
        for g in self.global_block_indices:
            if g < n:
                L[:, g] = True
                L[g, :] = True
        return L


@dataclasses.dataclass(frozen=True)
class VariableSparsityConfig(SparsityConfig):
    """Variable local window sizes + global blocks (reference:
    VariableSparsityConfig, simplified: per-row window grows with distance
    from the start)."""
    num_global_blocks: int = 1
    local_window_blocks: Tuple[int, ...] = (4,)

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = seq_len // self.block
        L = np.zeros((n, n), bool)
        windows = list(self.local_window_blocks)
        start = 0
        wi = 0
        while start < n:
            w = windows[min(wi, len(windows) - 1)]
            end = min(start + w, n)
            L[start:end, start:end] = True
            start, wi = end, wi + 1
        L[:, :min(self.num_global_blocks, n)] = True
        return L


_MODES = {
    "dense": DenseSparsityConfig,
    "fixed": FixedSparsityConfig,
    "bigbird": BigBirdSparsityConfig,
    "bslongformer": BSLongformerSparsityConfig,
    "variable": VariableSparsityConfig,
}


def get_sparsity_config(mode: str, **kw) -> SparsityConfig:
    if mode not in _MODES:
        raise ValueError(f"unknown sparse attention mode {mode!r}; "
                         f"have {sorted(_MODES)}")
    return _MODES[mode](**kw)


def _adjacency(layout: np.ndarray, causal: bool):
    """layout [Qb, Kb] -> (idx [Qb, max_deg] int32 padded -1, count [Qb]),
    plus the transpose for the dK/dV pass."""
    n = layout.shape[0]
    if causal:
        layout = layout & np.tril(np.ones((n, n), bool))
    rows = [np.nonzero(layout[i])[0] for i in range(n)]
    deg = max((len(r) for r in rows), default=0)
    idx = np.full((n, max(deg, 1)), -1, np.int32)
    for i, r in enumerate(rows):
        idx[i, :len(r)] = r
    count = np.array([len(r) for r in rows], np.int32)
    cols = [np.nonzero(layout[:, j])[0] for j in range(n)]
    cdeg = max((len(c) for c in cols), default=0)
    cidx = np.full((n, max(cdeg, 1)), -1, np.int32)
    for j, c in enumerate(cols):
        cidx[j, :len(c)] = c
    ccount = np.array([len(c) for c in cols], np.int32)
    return idx, count, cidx, ccount


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


# --------------------------------------------------------------------------
# kernels: flash-style online softmax over each row's adjacency list, with
# MANUAL double-buffered DMA — K/V stay in HBM (pltpu.ANY) and each listed
# block is copied into a 2-slot VMEM scratch one step ahead of its use.
# Work and HBM traffic are exactly proportional to the row's TRUE degree:
# no full-[S,D] VMEM residency (the round-2 design) and no padded grid
# steps (a slot-grid design pays max_deg steps per row, and global rows
# push max_deg to the full row width for BigBird/Longformer layouts).
# --------------------------------------------------------------------------

M_FLOOR = -1e20


# K/V (and the dK/dV pass's Q/dO) arrive CHANNEL-MAJOR ([B, N, D, S]): DMA
# slices then run along the 128-aligned sequence dim (Mosaic rejects lane
# slices of a D=64 minor dim). lse/delta keep [B, N, S, 1] — their minor dim
# is full. The dots below contract the channel dim of the transposed tiles
# directly, so no in-kernel transposes are needed.

def _seq_dma(hbm_ref, scratch, sem, b, n, j, slot, block):
    return pltpu.make_async_copy(
        hbm_ref.at[b, n, :, pl.ds(j * block, block)],
        scratch.at[slot], sem.at[slot])


def _make_dma_ops(streams, idx_ref, row, b, n, block):
    """Shared start/wait pair over a list of (hbm, scratch, sem) streams:
    descriptors are rebuilt identically for start and wait (the Pallas
    async-copy contract)."""
    def _descs(t, slot):
        j = jnp.maximum(idx_ref[row, t], 0)
        return [_seq_dma(hbm, scr, sem, b, n, j, slot, block)
                for hbm, scr, sem in streams]

    def start(t, slot):
        for d_ in _descs(t, slot):
            d_.start()

    def wait(t, slot):
        for d_ in _descs(t, slot):
            d_.wait()

    return start, wait


def _sp_fwd_kernel(idx_ref, cnt_ref, q_ref, kt_hbm, vt_hbm, o_ref, lse_ref,
                   *, sm_scale, causal, block):
    b, n, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    cnt = cnt_ref[qi]
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale     # [block, D]
    d = q.shape[-1]
    q_start = qi * block

    def body(ks, vs, ksem, vsem):
        start, wait = _make_dma_ops(
            [(kt_hbm, ks, ksem), (vt_hbm, vs, vsem)], idx_ref, qi, b, n,
            block)

        @pl.when(cnt > 0)
        def _warm():
            start(0, 0)

        def step(t, carry):
            m, l, acc = carry
            slot = t % 2

            @pl.when(t + 1 < cnt)
            def _prefetch():
                start(t + 1, (t + 1) % 2)

            wait(t, slot)
            j = idx_ref[qi, t]
            kt = ks[slot].astype(jnp.float32)           # [D, block]
            vt = vs[slot].astype(jnp.float32)
            # s[qr, kr] = sum_d q[qr, d] * kt[d, kr]
            s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if causal:
                q_pos = q_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 0)
                k_pos = j * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            m_new = jnp.maximum(
                jnp.maximum(m, jnp.max(s, -1, keepdims=True)), M_FLOOR)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
            # acc[qr, d] = sum_kr p[qr, kr] * vt[d, kr]
            acc_new = acc * alpha + jax.lax.dot_general(
                p, vt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((block, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block, 1), jnp.float32)
        acc0 = jnp.zeros((block, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, cnt, step, (m0, l0, acc0))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m + jnp.log(l_safe)

    pl.run_scoped(
        body,
        ks=pltpu.VMEM((2, kt_hbm.shape[2], block), kt_hbm.dtype),
        vs=pltpu.VMEM((2, vt_hbm.shape[2], block), vt_hbm.dtype),
        ksem=pltpu.SemaphoreType.DMA((2,)),
        vsem=pltpu.SemaphoreType.DMA((2,)))


def _sp_bwd_dq_kernel(idx_ref, cnt_ref, q_ref, kt_hbm, vt_hbm, do_ref,
                      lse_ref, delta_ref, dq_ref, *, sm_scale, causal,
                      block):
    b, n, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    cnt = cnt_ref[qi]
    q_start = qi * block
    q = q_ref[0, 0].astype(jnp.float32)                 # [block, D]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    d = q.shape[-1]

    def body(ks, vs, ksem, vsem):
        start, wait = _make_dma_ops(
            [(kt_hbm, ks, ksem), (vt_hbm, vs, vsem)], idx_ref, qi, b, n,
            block)

        @pl.when(cnt > 0)
        def _warm():
            start(0, 0)

        def step(t, dq):
            slot = t % 2

            @pl.when(t + 1 < cnt)
            def _prefetch():
                start(t + 1, (t + 1) % 2)

            wait(t, slot)
            j = idx_ref[qi, t]
            kt = ks[slot].astype(jnp.float32)           # [D, block]
            vt = vs[slot].astype(jnp.float32)
            s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32) \
                * sm_scale
            if causal:
                q_pos = q_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 0)
                k_pos = j * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            p = jnp.exp(s - lse)
            # dp[qr, kr] = sum_d do[qr, d] * vt[d, kr]
            dp = jax.lax.dot_general(do, vt, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * sm_scale
            # dq[qr, d] = sum_kr ds[qr, kr] * kt[d, kr]
            return dq + jax.lax.dot_general(
                ds, kt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)

        dq = jax.lax.fori_loop(0, cnt, step,
                               jnp.zeros((block, d), jnp.float32))
        dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    pl.run_scoped(
        body,
        ks=pltpu.VMEM((2, kt_hbm.shape[2], block), kt_hbm.dtype),
        vs=pltpu.VMEM((2, vt_hbm.shape[2], block), vt_hbm.dtype),
        ksem=pltpu.SemaphoreType.DMA((2,)),
        vsem=pltpu.SemaphoreType.DMA((2,)))


def _sp_bwd_dkv_kernel(cidx_ref, ccnt_ref, qt_hbm, k_ref, v_ref, dot_hbm,
                       lset_hbm, deltat_hbm, dk_ref, dv_ref, *, sm_scale,
                       causal, block):
    """Computes in TRANSPOSED score space (s_t[kr, qr]) so the per-q-row
    lse/delta broadcast along lanes — their [B, N, 1, S] layout gives
    128-aligned DMA slices with no in-kernel transposes."""
    b, n, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    cnt = ccnt_ref[ki]
    k_start = ki * block
    k = k_ref[0, 0].astype(jnp.float32)                 # [block, D]
    v = v_ref[0, 0].astype(jnp.float32)
    d = k.shape[-1]

    def body(qs, dos, ls, dls, qsem, dosem, lsem, dlsem):
        start, wait = _make_dma_ops(
            [(qt_hbm, qs, qsem), (dot_hbm, dos, dosem),
             (lset_hbm, ls, lsem), (deltat_hbm, dls, dlsem)],
            cidx_ref, ki, b, n, block)

        @pl.when(cnt > 0)
        def _warm():
            start(0, 0)

        def step(t, carry):
            dk, dv = carry
            slot = t % 2

            @pl.when(t + 1 < cnt)
            def _prefetch():
                start(t + 1, (t + 1) % 2)

            wait(t, slot)
            i = cidx_ref[ki, t]
            qt = qs[slot].astype(jnp.float32)           # [D, block]
            dot_ = dos[slot].astype(jnp.float32)        # [D, block]
            lse_row = ls[slot]                          # [1, block]
            delta_row = dls[slot]
            # s_t[kr, qr] = sum_d k[kr, d] * qt[d, qr]
            s_t = jax.lax.dot_general(k, qt, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32) \
                * sm_scale
            if causal:
                k_pos = k_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 0)
                q_pos = i * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, block), 1)
                s_t = jnp.where(q_pos >= k_pos, s_t, NEG_INF)
            p_t = jnp.exp(s_t - lse_row)                # [bk, bq]
            # dv[kr, d] = sum_qr p_t[kr, qr] * dot_[d, qr]
            dv_new = dv + jax.lax.dot_general(
                p_t, dot_, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            # dp_t[kr, qr] = sum_d v[kr, d] * dot_[d, qr]
            dp_t = jax.lax.dot_general(v, dot_, (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
            ds_t = p_t * (dp_t - delta_row) * sm_scale
            # dk[kr, d] = sum_qr ds_t[kr, qr] * qt[d, qr]
            dk_new = dk + jax.lax.dot_general(
                ds_t, qt, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk_new, dv_new

        dk0 = jnp.zeros((block, d), jnp.float32)
        dv0 = jnp.zeros((block, d), jnp.float32)
        dk, dv = jax.lax.fori_loop(0, cnt, step, (dk0, dv0))
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv.astype(dv_ref.dtype)

    pl.run_scoped(
        body,
        qs=pltpu.VMEM((2, qt_hbm.shape[2], block), qt_hbm.dtype),
        dos=pltpu.VMEM((2, dot_hbm.shape[2], block), dot_hbm.dtype),
        ls=pltpu.VMEM((2, 1, block), jnp.float32),
        dls=pltpu.VMEM((2, 1, block), jnp.float32),
        qsem=pltpu.SemaphoreType.DMA((2,)),
        dosem=pltpu.SemaphoreType.DMA((2,)),
        lsem=pltpu.SemaphoreType.DMA((2,)),
        dlsem=pltpu.SemaphoreType.DMA((2,)))


def _compiler_params():
    if _interpret():
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel"))


def _sp_fwd(q, k, v, idx, cnt, sm_scale, causal, block):
    B, N, S, D = q.shape
    blk = pl.BlockSpec((1, 1, block, D),
                       lambda b, n, i, idx_, cnt_: (b, n, i, 0),
                       memory_space=pltpu.VMEM)
    hbm = pl.BlockSpec(memory_space=pl.ANY)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, N, S // block),
        in_specs=[blk, hbm, hbm],
        out_specs=[
            blk,
            pl.BlockSpec((1, 1, block, 1),
                         lambda b, n, i, idx_, cnt_: (b, n, i, 0),
                         memory_space=pltpu.VMEM),
        ],
    )
    kernel = functools.partial(_sp_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block=block)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, N, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, N, S, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(idx, cnt, q, jnp.swapaxes(k, 2, 3), jnp.swapaxes(v, 2, 3))
    return o, lse


def _sp_bwd(sm_scale, causal, block, adjacency, residuals, g):
    q, k, v, o, lse = residuals
    idx, cnt, cidx, ccnt = adjacency
    do = g
    B, N, S, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    blk = pl.BlockSpec((1, 1, block, D),
                       lambda b, n, i, idx_, cnt_: (b, n, i, 0),
                       memory_space=pltpu.VMEM)
    blk_vec = pl.BlockSpec((1, 1, block, 1),
                           lambda b, n, i, idx_, cnt_: (b, n, i, 0),
                           memory_space=pltpu.VMEM)
    hbm = pl.BlockSpec(memory_space=pl.ANY)

    dq = pl.pallas_call(
        functools.partial(_sp_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block=block),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, N, S // block),
            in_specs=[blk, hbm, hbm, blk, blk_vec, blk_vec],
            out_specs=blk),
        out_shape=jax.ShapeDtypeStruct((B, N, S, D), q.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(idx, cnt, q, jnp.swapaxes(k, 2, 3), jnp.swapaxes(v, 2, 3), do, lse,
      delta)

    # dK/dV pass: the grid's block index is a K block; Q/dO/lse/delta are
    # DMA'd per listed row of the TRANSPOSED adjacency (cidx)
    dk, dv = pl.pallas_call(
        functools.partial(_sp_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block=block),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, N, S // block),
            in_specs=[hbm, blk, blk, hbm, hbm, hbm],
            out_specs=[blk, blk]),
        out_shape=[jax.ShapeDtypeStruct((B, N, S, D), q.dtype),
                   jax.ShapeDtypeStruct((B, N, S, D), q.dtype)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(cidx, ccnt, jnp.swapaxes(q, 2, 3), k, v, jnp.swapaxes(do, 2, 3),
      jnp.swapaxes(lse, 2, 3), jnp.swapaxes(delta, 2, 3))
    return dq, dk, dv


# adjacency travels as nested tuples (hashable: custom_vjp nondiff args and
# jit static closure both require it); materialized to arrays at use
def _adj_arrays(adjacency):
    return tuple(np.asarray(a, np.int32) for a in adjacency)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _sparse(q, k, v, adjacency, sm_scale, causal, block):
    idx, cnt, _, _ = _adj_arrays(adjacency)
    o, _ = _sp_fwd(q, k, v, jnp.asarray(idx), jnp.asarray(cnt), sm_scale,
                   causal, block)
    return o


def _sparse_fwd(q, k, v, adjacency, sm_scale, causal, block):
    idx, cnt, _, _ = _adj_arrays(adjacency)
    o, lse = _sp_fwd(q, k, v, jnp.asarray(idx), jnp.asarray(cnt), sm_scale,
                     causal, block)
    return o, (q, k, v, o, lse)


def _sparse_bwd(adjacency, sm_scale, causal, block, residuals, g):
    adjacency = tuple(jnp.asarray(a) for a in _adj_arrays(adjacency))
    return _sp_bwd(sm_scale, causal, block, adjacency, residuals, g)


_sparse.defvjp(_sparse_fwd, _sparse_bwd)


@functools.lru_cache(maxsize=64)
def _cached_adjacency(config: SparsityConfig, seq_len: int, causal: bool):
    layout = config.make_layout(seq_len)
    idx, cnt, cidx, ccnt = _adjacency(layout, causal)
    return (tuple(map(tuple, idx)), tuple(cnt),
            tuple(map(tuple, cidx)), tuple(ccnt))


def sparse_attention(q, k, v, config: SparsityConfig, *, causal: bool = True,
                     sm_scale: Optional[float] = None):
    """Block-sparse attention. q, k, v: [B, S, N, D] -> [B, S, N, D].

    The layout is built once per (config, S, causal) and baked into the
    compiled kernel as SMEM index tables (reference:
    sparse_self_attention.py:11 forward)."""
    B, S, N, D = q.shape
    if S % config.block:
        raise ValueError(f"seq len {S} not divisible by block {config.block}")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    raw = _cached_adjacency(config, S, bool(causal))
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _sparse(qt, kt, vt, raw, float(sm_scale), bool(causal),
                config.block)
    return jnp.swapaxes(o, 1, 2)


def reference_sparse_attention(q, k, v, config: SparsityConfig, *,
                               causal: bool = True,
                               sm_scale: Optional[float] = None):
    """XLA reference: dense attention masked by the block layout."""
    B, S, N, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    layout = config.make_layout(S)
    mask = np.repeat(np.repeat(layout, config.block, 0), config.block, 1)
    if causal:
        mask = mask & np.tril(np.ones((S, S), bool))
    s = jnp.einsum("bsnd,btnd->bnst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s = jnp.where(jnp.asarray(mask)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.asarray(mask)[None, None], p, 0.0)
    return jnp.einsum("bnst,btnd->bsnd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
