"""Op/optimizer registry.

Reference: ``op_builder/all_ops.py`` + ``deepspeed/runtime/engine.py:1225``
(``_configure_basic_optimizer`` name dispatch). There is no JIT-build step on
TPU — Pallas kernels compile with XLA — so the registry is a plain name->factory
table plus a compatibility report used by ``ds_report``.
"""

SUPPORTED_OPTIMIZERS = {
    "adam", "adamw", "fusedadam", "sgd", "lamb", "fusedlamb", "adagrad",
    "onebitadam", "onebitlamb", "zerooneadam", "lion", "cpuadam", "cpuadagrad",
}


def get_optimizer_builder(name: str):
    from deepspeed_tpu.ops.adam import adam as adam_fn, adamw
    from deepspeed_tpu.ops.lamb import lamb as lamb_fn
    from deepspeed_tpu.ops.onebit import (
        onebit_adam, onebit_lamb, zero_one_adam)
    from deepspeed_tpu.ops.optimizers import sgd, adagrad, lion
    name = name.lower()
    table = {
        "adam": adam_fn,
        "fusedadam": adam_fn,
        "adamw": adamw,
        "cpuadam": adamw,       # host-offloaded variant selected by offload config
        "sgd": sgd,
        "lamb": lamb_fn,
        "fusedlamb": lamb_fn,
        "onebitlamb": onebit_lamb,
        "adagrad": adagrad,
        "cpuadagrad": adagrad,
        "lion": lion,
        "onebitadam": onebit_adam,
        "zerooneadam": zero_one_adam,
    }
    if name not in table:
        raise ValueError(f"unknown optimizer '{name}'")
    return table[name]


def op_report():
    """Name -> availability, for the ds_report CLI (reference: env_report.py)."""
    report = {}
    try:
        from jax.experimental import pallas  # noqa: F401
        report["pallas"] = True
    except ImportError:
        report["pallas"] = False
    modules = {
        "flash_attention": "deepspeed_tpu.ops.flash_attention",
        "fused_adam": "deepspeed_tpu.ops.adam",
        "layer_norm": "deepspeed_tpu.ops.layer_norm",
        "quantizer": "deepspeed_tpu.ops.quantizer",
        "block_sparse_attention": "deepspeed_tpu.ops.sparse_attention",
        "rotary": "deepspeed_tpu.models.transformer",
    }
    import importlib
    for op, mod in modules.items():
        try:
            importlib.import_module(mod)
            report[op] = report["pallas"]
        except ImportError:
            report[op] = False
    try:
        from deepspeed_tpu.ops.aio import aio_available
        report["async_io"] = aio_available()
    except Exception:
        report["async_io"] = False
    return report
