from deepspeed_tpu.ops.registry import SUPPORTED_OPTIMIZERS, get_optimizer_builder, op_report
from deepspeed_tpu.ops.optimizers import Optimizer, sgd, adagrad, lion, global_grad_norm
from deepspeed_tpu.ops.adam import adam, adamw
from deepspeed_tpu.ops.onebit import onebit_adam, onebit_lamb, zero_one_adam, PhasedOptimizer
from deepspeed_tpu.ops.lamb import lamb
