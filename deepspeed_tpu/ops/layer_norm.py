"""Fused normalization ops.

Reference: ``csrc/transformer/inference/csrc/layer_norm.cu`` (fused
layer-norm / rms-norm with optional residual-add). On TPU, XLA fuses the
reduction + scale chain into one VPU pass over the row, so these are plain
jnp formulations — kept as a module so kernels stay swappable (a Pallas
variant can slot in) and `op_report` reflects a real op.
"""

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["layer_norm", "rms_norm", "fused_add_layer_norm",
           "fused_add_rms_norm"]


def layer_norm(x, scale, bias=None, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm(x, scale, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(
        x.dtype)


def fused_add_layer_norm(x, residual, scale, bias=None, eps: float = 1e-5):
    """(x + residual) then layer_norm — the reference's fused residual path;
    returns (normed, new_residual)."""
    s = x + residual
    return layer_norm(s, scale, bias, eps), s


def fused_add_rms_norm(x, residual, scale, eps: float = 1e-5):
    s = x + residual
    return rms_norm(s, scale, eps), s
