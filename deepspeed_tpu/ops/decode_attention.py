"""Pallas decode attention: single-token attention against the KV cache,
reading ONLY the valid prefix.

Capability-equivalent of the reference's fused softmax_context decode kernels
(``csrc/transformer/inference/csrc/softmax.cu``, bound at
``pt_binding.cpp:1716-1780``): those fuse the softmax over the accumulated
context; here the whole (QK^T -> online softmax -> PV) runs in one kernel.

Why a kernel at all: decode is HBM-bandwidth-bound on the KV cache, and the
XLA fallback masks AFTER reading — every step touches all ``max_len`` rows.
This kernel makes the cache read length-aware: the current position arrives
as a scalar-prefetch argument, the KV block index map clamps invalid steps
to the last valid block (the pipeline emitter elides same-index DMAs), and
``pl.when`` skips their compute — so a step at position t reads O(t) bytes,
not O(max_len).

GQA-native like the training kernel: grid over KV heads, each program holds
the whole [rep, D] query group; K/V are read once per group.

Layout: q [B, 1, Nq, D]; cache k/v [B, Nkv, T, D].

Two masking modes:
- kv_row=None: the newest row was already written into the buffer; valid
  rows are <= index (legacy contract).
- kv_row=(k_row, v_row) [B, Nkv, 1, D]: the fresh row stays OUT of the
  buffer (the decode loop writes all layers' rows in one tiny update — see
  models/transformer.py decode_step); buffer rows < index are valid and the
  fresh row's logit is folded into the online softmax at finalize.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30
M_FLOOR = -1e20


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _kernel(idx_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s, *,
            sm_scale, rep, block_k):
    """Grid (B, num_kv_blocks); one program holds ALL kv heads for one
    batch row (a batched dot over the head dim keeps per-step work large
    enough to amortize grid overhead). idx_ref[0] = last valid buffer
    position (may be -1: nothing valid)."""
    j = pl.program_id(1)
    nt = pl.num_programs(1)
    idx = idx_ref[0]
    nkv, d = q_ref.shape[1], q_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    @pl.when(j * block_k <= idx)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale     # [nkv, rep, d]
        k = k_ref[0].astype(jnp.float32)                # [nkv, bk, d]
        v = v_ref[0].astype(jnp.float32)
        # batched over kv heads: [nkv, rep, bk]
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32)
        t_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (nkv, rep, block_k), 2)
        s = jnp.where(t_pos <= idx, s, NEG_INF)
        m = m_s[:, 0:rep, 0:1]
        l = l_s[:, 0:rep, 0:1]
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, -1, keepdims=True)),
                            M_FLOOR)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((2,), (1,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        acc_s[:, 0:rep] = acc_s[:, 0:rep] * alpha + pv
        m_s[:, 0:rep] = jnp.broadcast_to(m_new, (nkv, rep, m_s.shape[2]))
        l_s[:, 0:rep] = jnp.broadcast_to(l_new, (nkv, rep, l_s.shape[2]))

    @pl.when(j == nt - 1)
    def _finalize():
        l = l_s[:, 0:rep, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_s[:, 0:rep] / l_safe).astype(o_ref.dtype)


def _kernel_row(idx_ref, q_ref, k_ref, v_ref, kr_ref, vr_ref, o_ref,
                m_s, l_s, acc_s, *, sm_scale, rep, block_k):
    """Like _kernel, plus the CURRENT token's (k, v) row folded into the
    online softmax at finalize (the row is not in the buffer)."""
    _kernel(idx_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s,
            sm_scale=sm_scale, rep=rep, block_k=block_k)
    j = pl.program_id(1)
    nt = pl.num_programs(1)
    nkv, d = q_ref.shape[1], q_ref.shape[-1]

    @pl.when(j == nt - 1)
    def _fold_row():
        q = q_ref[0].astype(jnp.float32) * sm_scale       # [nkv, rep, d]
        kr = kr_ref[0].astype(jnp.float32)                # [nkv, 1, d]
        vr = vr_ref[0].astype(jnp.float32)
        s1 = jax.lax.dot_general(q, kr, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        m = m_s[:, 0:rep, 0:1]
        l = l_s[:, 0:rep, 0:1]
        m_new = jnp.maximum(jnp.maximum(m, s1), M_FLOOR)
        p1 = jnp.exp(s1 - m_new)                          # [nkv, rep, 1]
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p1
        acc = acc_s[:, 0:rep] * alpha + p1 * vr           # [nkv, rep, d]
        l_safe = jnp.where(l_new == 0.0, 1.0, l_new)
        o_ref[0] = (acc / l_safe).astype(o_ref.dtype)


def decode_attention(q, ck, cv, index, *, kv_row=None,
                     sm_scale: Optional[float] = None,
                     block_k: int = DEFAULT_BLOCK_K):
    """q: [B, 1, Nq, D]; ck/cv: [B, Nkv, T, D]. Returns [B, 1, Nq, D].

    kv_row=None: valid buffer rows are <= index (row already written).
    kv_row=(k_row, v_row): valid rows are < index; the fresh row joins the
    softmax separately. Reads only cache blocks covering valid positions.
    """
    B, _, Nq, D = q.shape
    Nkv, T = ck.shape[1], ck.shape[2]
    rep = Nq // Nkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    bk = min(block_k, T)
    while T % bk:
        bk //= 2
    nt = T // bk
    qg = q.reshape(B, Nkv, rep, D)
    # last valid buffer position: index (legacy) or index-1 (row mode)
    last = jnp.asarray(index, jnp.int32) - (1 if kv_row is not None else 0)
    idx = last.reshape(1)

    def kv_index(b, j, idx_ref):
        # index maps receive (*grid_indices, *scalar_prefetch_refs); clamp
        # invalid steps to the last valid block so their DMAs are elided
        last_valid = jax.lax.div(jnp.maximum(idx_ref[0], 0), bk)
        return (b, 0, jnp.minimum(j, last_valid), 0)

    kv_spec = pl.BlockSpec((1, Nkv, bk, D), kv_index,
                           memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((1, Nkv, rep, D), lambda b, j, i: (b, 0, 0, 0),
                     memory_space=pltpu.VMEM),
        kv_spec, kv_spec,
    ]
    args = [idx, qg, ck, cv]
    kernel = functools.partial(_kernel, sm_scale=float(sm_scale), rep=rep,
                               block_k=bk)
    if kv_row is not None:
        k_row, v_row = kv_row
        row_spec = pl.BlockSpec((1, Nkv, 1, D), lambda b, j, i: (b, 0, 0, 0),
                                memory_space=pltpu.VMEM)
        in_specs += [row_spec, row_spec]
        args += [k_row, v_row]
        kernel = functools.partial(_kernel_row, sm_scale=float(sm_scale),
                                   rep=rep, block_k=bk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Nkv, rep, D),
                               lambda b, j, i: (b, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((Nkv, max(rep, 8), 128), jnp.float32),   # m
            pltpu.VMEM((Nkv, max(rep, 8), 128), jnp.float32),   # l
            pltpu.VMEM((Nkv, max(rep, 8), D), jnp.float32),     # acc
        ],
    )
    compiler_params = None if _interpret() else pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"))
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Nkv, rep, D), q.dtype),
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(*args)
    return o.reshape(B, 1, Nq, D)
