"""Pallas paged decode attention: single-token attention against a BLOCK
POOL through per-sequence block tables, reading ONLY the blocks that cover
each slot's valid prefix.

Capability-equivalent of the reference's fused softmax_context decode
kernels (``csrc/transformer/inference/csrc/softmax.cu``, bound at
``pt_binding.cpp:1716-1780``) lifted to the vLLM-style paged layout: the
fixed decode workspace of ``inference_context.h`` becomes a pool of
fixed-size blocks shared across requests, and the gather that XLA would
materialize per step is resolved inside the kernel's index maps instead.

Why a kernel HERE (and not for the old contiguous ring buffer): on the
contiguous layout the windowed-XLA loop already reads O(valid) bytes via
static slices, and the per-layer pallas_call overhead lost end-to-end on
v5e — that kernel was deleted (VERDICT r5 weak #4). On the PAGED layout the
XLA fallback must materialize a [S, MB*bs, Nkv, D] gather of every slot's
table every step — a full extra HBM write+read of the working set. Here the
block table rides scalar prefetch, the KV index map translates (slot, j) ->
pool block directly, steps beyond a slot's valid prefix clamp to its last
valid block (the pipeline emitter elides same-index DMAs), and ``pl.when``
skips their compute — per-step HBM traffic is exactly the valid blocks,
with no materialized gather. Whether this beats the XLA gather on given
pool shapes is decided by a measured micro-bench at serving-engine init
(inference/serving.py), not a flag.

GQA-native like the training kernel: each program holds the whole
[Nkv, rep, D] query group of one slot; K/V blocks are read once per group.

Layout: q [S, 1, Nq, D] (one in-flight token per slot); pools
[NB, Nkv, bs, D]; block_tables [S, MB] int32 (entry 0 = reserved trash
block — never valid, masked by seq_lens); seq_lens [S] int32 = valid
prefix length per slot. The CURRENT token's (k, v) row arrives separately
(kv_row) and folds into the online softmax at finalize — the caller
scatters it into the pool afterwards, keeping the per-step pool update
O(row), exactly like the ring-buffer path.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
M_FLOOR = -1e20


def _interpret() -> bool:
    return jax.default_backend() not in ("tpu", "axon")


def _kernel(tab_ref, len_ref, q_ref, k_ref, v_ref, kr_ref, vr_ref, o_ref,
            m_s, l_s, acc_s, *, sm_scale, rep, block_size):
    """Grid (S, MB): program (s, j) folds block_tables[s, j] into slot s's
    online softmax. len_ref[s] = valid prefix length (rows < len are
    valid); the fresh (k, v) row joins at finalize."""
    s = pl.program_id(0)
    j = pl.program_id(1)
    nt = pl.num_programs(1)
    ln = len_ref[s]
    nkv, d = q_ref.shape[1], q_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    @pl.when(j * block_size < ln)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale     # [nkv, rep, d]
        k = k_ref[0].astype(jnp.float32)                # [nkv, bs, d]
        v = v_ref[0].astype(jnp.float32)
        # batched over kv heads: [nkv, rep, bs]
        sc = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        t_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (nkv, rep, block_size), 2)
        sc = jnp.where(t_pos < ln, sc, NEG_INF)
        m = m_s[:, 0:rep, 0:1]
        l = l_s[:, 0:rep, 0:1]
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(sc, -1, keepdims=True)),
                            M_FLOOR)
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((2,), (1,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        acc_s[:, 0:rep] = acc_s[:, 0:rep] * alpha + pv
        m_s[:, 0:rep] = jnp.broadcast_to(m_new, (nkv, rep, m_s.shape[2]))
        l_s[:, 0:rep] = jnp.broadcast_to(l_new, (nkv, rep, l_s.shape[2]))

    @pl.when(j == nt - 1)
    def _finalize():
        # fold the CURRENT token's row (not yet in the pool), then emit
        q = q_ref[0].astype(jnp.float32) * sm_scale       # [nkv, rep, d]
        kr = kr_ref[0].astype(jnp.float32)                # [nkv, 1, d]
        vr = vr_ref[0].astype(jnp.float32)
        s1 = jax.lax.dot_general(q, kr, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        m = m_s[:, 0:rep, 0:1]
        l = l_s[:, 0:rep, 0:1]
        m_new = jnp.maximum(jnp.maximum(m, s1), M_FLOOR)
        p1 = jnp.exp(s1 - m_new)                          # [nkv, rep, 1]
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p1
        acc = acc_s[:, 0:rep] * alpha + p1 * vr           # [nkv, rep, d]
        l_safe = jnp.where(l_new == 0.0, 1.0, l_new)
        o_ref[0] = (acc / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_tables, seq_lens, *,
                           kv_row=None, sm_scale: Optional[float] = None):
    """q: [S, 1, Nq, D]; k_pool/v_pool: [NB, Nkv, bs, D]; block_tables:
    [S, MB] int32; seq_lens: [S] int32. Returns [S, 1, Nq, D].

    Valid pool rows for slot s are positions < seq_lens[s] (the fresh row
    is NOT in the pool — it arrives as kv_row=(k_row, v_row)
    [S, Nkv, 1, D] and joins the softmax at finalize). Blocks past a
    slot's valid prefix clamp to its last valid block in the index map, so
    their DMAs are elided and per-step HBM traffic is O(valid prefix).
    """
    S, one, Nq, D = q.shape
    NB, Nkv, bs, _ = k_pool.shape
    MB = block_tables.shape[1]
    rep = Nq // Nkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if kv_row is None:
        raise ValueError("paged_decode_attention requires the fresh-row "
                         "fold (kv_row): the serving decode step never "
                         "pre-writes the current token into the pool")
    k_row, v_row = kv_row
    qg = q.reshape(S, Nkv, rep, D)
    tables = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(seq_lens, jnp.int32)

    def kv_index(s, j, tab_ref, len_ref):
        # clamp steps past the valid prefix to the LAST valid block: the
        # pipeline emitter elides the repeated DMA and pl.when skips the
        # compute. len == 0 (fresh slot) clamps to entry 0 (trash block).
        ln = len_ref[s]
        last_valid = jnp.maximum(jax.lax.div(ln + bs - 1, bs) - 1, 0)
        return (tab_ref[s, jnp.minimum(j, last_valid)], 0, 0, 0)

    q_spec = pl.BlockSpec((1, Nkv, rep, D), lambda s, j, t, ln: (s, 0, 0, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, Nkv, bs, D), kv_index,
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, Nkv, 1, D), lambda s, j, t, ln: (s, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # (block_tables, seq_lens)
        grid=(S, MB),
        in_specs=[q_spec, kv_spec, kv_spec, row_spec, row_spec],
        out_specs=pl.BlockSpec((1, Nkv, rep, D),
                               lambda s, j, t, ln: (s, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((Nkv, max(rep, 8), 128), jnp.float32),   # m
            pltpu.VMEM((Nkv, max(rep, 8), 128), jnp.float32),   # l
            pltpu.VMEM((Nkv, max(rep, 8), D), jnp.float32),     # acc
        ],
    )
    kernel = functools.partial(_kernel, sm_scale=float(sm_scale), rep=rep,
                               block_size=bs)
    compiler_params = None if _interpret() else pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"))
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Nkv, rep, D), q.dtype),
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(tables, lens, qg, k_pool, v_pool, k_row, v_row)
    return o.reshape(S, 1, Nq, D)
