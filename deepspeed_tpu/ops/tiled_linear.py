"""Tiled / memory-efficient linear layers.

Reference: ``deepspeed/runtime/zero/tiling.py:29`` (TiledLinear — split a
huge Linear into tiles so ZeRO-3 partitions/gathers one tile at a time) and
``runtime/zero/linear.py:42,122`` (LinearFunctionForZeroStage3 — all-gather
the weight in BACKWARD instead of saving the gathered copy).

TPU-native re-design:
- gather-in-backward is ``jax.checkpoint`` with a policy that refuses to
  save the (GSPMD-gathered) weight: backward re-gathers, so peak residency
  never holds both the activation grads and a saved gathered weight.
- tiling is a ``lax.scan``/python loop over weight column tiles with each
  tile's matmul rematerialized — the live set is one tile's output grad
  plus one gathered tile, whatever the full layer size. Under a ZeRO-3
  mesh each tile is itself fsdp-sharded, so the in-graph all-gather per
  tile IS the reference's per-tile fetch.
"""

import jax
import jax.numpy as jnp


def memory_efficient_linear(x, w, b=None):
    """y = x @ w (+ b) with NOTHING saved for backward except the raw
    (sharded) inputs — the reference's gather-weight-in-backward.

    Wrap the hot projections of a huge model with this when the saved
    gathered weights dominate activation memory (reference:
    linear.py:42)."""
    def f(x, w):
        return x @ w.astype(x.dtype)

    y = jax.checkpoint(
        f, policy=jax.checkpoint_policies.nothing_saveable)(x, w)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


def tiled_linear(x, w, b=None, *, out_tiles: int = 1, in_tiles: int = 1):
    """y = x @ w (+ b), computed over an (in_tiles x out_tiles) grid of
    weight tiles with per-tile rematerialization (reference: TiledLinear,
    tiling.py:29 — same splits, expressed as a compiled loop instead of
    submodule surgery). Tile edges handle non-divisible dims.

    x: [..., In]; w: [In, Out]; returns [..., Out].
    """
    In, Out = w.shape
    out_tiles = max(1, min(out_tiles, Out))
    in_tiles = max(1, min(in_tiles, In))
    row_cut = [round(i * In / in_tiles) for i in range(in_tiles + 1)]
    col_cut = [round(j * Out / out_tiles) for j in range(out_tiles + 1)]

    def tile_mm(xs, ws):
        return xs @ ws.astype(xs.dtype)

    tile_mm = jax.checkpoint(tile_mm,
                             policy=jax.checkpoint_policies.nothing_saveable)

    cols = []
    for j in range(out_tiles):
        wcol = w[:, col_cut[j]:col_cut[j + 1]]
        acc = None
        for i in range(in_tiles):
            xs = x[..., row_cut[i]:row_cut[i + 1]]
            part = tile_mm(xs, wcol[row_cut[i]:row_cut[i + 1]])
            acc = part if acc is None else acc + part
        cols.append(acc)
    y = jnp.concatenate(cols, axis=-1) if len(cols) > 1 else cols[0]
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


def split_tiled_weight(w, out_tiles: int):
    """Offline helper mirroring TiledLinear.copy_params_from splitting: a
    full [In, Out] weight into the per-tile list the reference's module
    holds (useful for porting reference-tiled checkpoints)."""
    Out = w.shape[1]
    cut = [round(j * Out / out_tiles) for j in range(out_tiles + 1)]
    return [w[:, cut[j]:cut[j + 1]] for j in range(out_tiles)]
