"""Native host-side fused AdamW (ctypes binding).

Reference: ``deepspeed/ops/adam/cpu_adam.py:12`` (DeepSpeedCPUAdam) over
``csrc/adam/cpu_adam.cpp`` — the compute half of ZeRO-Offload: fp32
master/m/v stay in host DRAM and the optimizer runs on host cores, so per
step only bf16 grads cross down and bf16 params cross up (4 bytes/param
instead of 28). Built JIT with g++ -O3 -march=native -fopenmp (the
autovectorizer covers the reference's hand-rolled AVX macros).
"""

import ctypes
import hashlib
import math
import os
import subprocess
from typing import Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "adam", "dstpu_cpu_adam.cpp")

_LIB = None


def _cache_dir() -> str:
    base = os.environ.get("DSTPU_CACHE_DIR") or os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "deepspeed_tpu")
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_cache_dir(), f"libdstpu_cpu_adam-{digest}.so")
    if os.path.exists(so):
        return so
    tmp = f"{so}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except Exception as e:  # pragma: no cover - toolchain missing
        logger.warning(f"cpu_adam build failed: {e}")
        return None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    so = _build()
    if so is None:
        return None
    lib = ctypes.CDLL(so)
    f32p = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    lib.dstpu_adam_step_bf16.argtypes = [
        f32p, f32p, f32p, u16p, u16p, ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int, ctypes.c_float, ctypes.c_float,
        ctypes.c_float]
    lib.dstpu_adam_step_f32.argtypes = [
        f32p, f32p, f32p, f32p, f32p, ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int, ctypes.c_float, ctypes.c_float,
        ctypes.c_float]
    lib.dstpu_sq_norm_bf16.restype = ctypes.c_double
    lib.dstpu_sq_norm_bf16.argtypes = [u16p, ctypes.c_int64]
    lib.dstpu_sq_norm_f32.restype = ctypes.c_double
    lib.dstpu_sq_norm_f32.argtypes = [f32p, ctypes.c_int64]
    _LIB = lib
    return lib


def cpu_adam_available() -> bool:
    return _load() is not None


def adam_step_flat(master: np.ndarray, m: np.ndarray, v: np.ndarray,
                   grads: np.ndarray, *, step_num: int, lr: float,
                   betas=(0.9, 0.999), eps: float = 1e-8,
                   weight_decay: float = 0.0, adamw_mode: bool = True,
                   bias_correction: bool = True, grad_scale: float = 1.0,
                   out: Optional[np.ndarray] = None):
    """One fused AdamW step over caller-owned flat fp32 state buffers
    (updated in place). grads: float32, or bf16 bits as uint16. If ``out``
    is given the updated params are also written there (uint16 bf16 bits
    for bf16 grads, float32 otherwise); pass None to only advance state.
    The chunk-granular entry the layer-streamed executor uses — state
    layout belongs to the caller, unlike the CPUAdam class which owns it."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native cpu_adam library unavailable")
    b1, b2 = betas
    if bias_correction:
        c1 = 1.0 - b1 ** step_num
        c2 = 1.0 - b2 ** step_num
    else:
        c1 = c2 = 1.0
    g = np.ascontiguousarray(grads).reshape(-1)
    n = g.size
    # validate every buffer handed to the C kernel as a raw pointer — a
    # short/misdtyped array would be silent native memory corruption
    for name, arr in (("master", master), ("m", m), ("v", v)):
        if arr.size != n or arr.dtype != np.float32 \
                or not arr.flags.c_contiguous:
            raise ValueError(
                f"{name}: need contiguous float32[{n}], got "
                f"{arr.dtype}[{arr.size}]"
                f"{'' if arr.flags.c_contiguous else ' (non-contiguous)'}")
    if out is not None:
        want = np.uint16 if g.dtype == np.uint16 else np.float32
        if out.size != n or out.dtype != want \
                or not out.flags.c_contiguous:
            raise ValueError(f"out: need contiguous {np.dtype(want).name}"
                             f"[{n}], got {out.dtype}[{out.size}]")
    f32p = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)

    def p(arr, ct):
        return arr.ctypes.data_as(ctypes.POINTER(ct))

    if g.dtype == np.uint16:
        lib.dstpu_adam_step_bf16(
            p(master, ctypes.c_float), p(m, ctypes.c_float),
            p(v, ctypes.c_float), p(g, ctypes.c_uint16),
            p(out, ctypes.c_uint16) if out is not None
            else ctypes.cast(None, u16p),
            n, float(lr), b1, b2, eps, weight_decay, int(adamw_mode),
            c1, c2, float(grad_scale))
    else:
        g = g.astype(np.float32, copy=False)
        lib.dstpu_adam_step_f32(
            p(master, ctypes.c_float), p(m, ctypes.c_float),
            p(v, ctypes.c_float), p(g, ctypes.c_float),
            p(out, ctypes.c_float) if out is not None
            else ctypes.cast(None, f32p),
            n, float(lr), b1, b2, eps, weight_decay, int(adamw_mode),
            c1, c2, float(grad_scale))


class CPUAdam:
    """Fused host AdamW over flat fp32 state buffers (master, m, v).

    State lives in numpy host memory owned by this object; step() consumes
    a flat grad array (bf16-bits uint16 or float32) and returns the updated
    params as bf16 bits (uint16) or fp32.
    """

    def __init__(self, n: int, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 bias_correction: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError("native cpu_adam library unavailable "
                               "(g++ build failed)")
        self._lib = lib
        self.n = int(n)
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.wd = weight_decay
        self.awm = adamw_mode
        self.bc = bias_correction
        self.master = np.zeros(self.n, np.float32)
        self.m = np.zeros(self.n, np.float32)
        self.v = np.zeros(self.n, np.float32)

    def load_master(self, params: np.ndarray):
        np.copyto(self.master, np.asarray(params, np.float32).reshape(-1))

    @staticmethod
    def _p(arr, ctype):
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    def sq_norm(self, grads: np.ndarray) -> float:
        g = np.ascontiguousarray(grads).reshape(-1)
        if g.dtype == np.uint16:
            return float(self._lib.dstpu_sq_norm_bf16(
                self._p(g, ctypes.c_uint16), g.size))
        g = g.astype(np.float32, copy=False)
        return float(self._lib.dstpu_sq_norm_f32(
            self._p(g, ctypes.c_float), g.size))

    def step(self, grads: np.ndarray, step_num: int, lr: Optional[float] = None,
             grad_scale: float = 1.0, out: Optional[np.ndarray] = None):
        """grads: uint16 (bf16 bits) or float32, length n. Returns updated
        params (uint16 bf16 bits for bf16 grads, else float32)."""
        g = np.ascontiguousarray(grads).reshape(-1)
        if out is None:
            out = np.empty(self.n,
                           np.uint16 if g.dtype == np.uint16 else np.float32)
        adam_step_flat(self.master, self.m, self.v, g, step_num=step_num,
                       lr=float(self.lr if lr is None else lr),
                       betas=(self.b1, self.b2), eps=self.eps,
                       weight_decay=self.wd, adamw_mode=self.awm,
                       bias_correction=self.bc, grad_scale=grad_scale,
                       out=out)
        return out

    def clip_coef(self, sq_total: float, clip: float,
                  grad_scale: float = 1.0) -> float:
        """Global-norm clip coefficient to fold into grad_scale."""
        gnorm = math.sqrt(sq_total) * grad_scale
        if clip and clip > 0 and gnorm > clip:
            return clip / (gnorm + 1e-6)
        return 1.0
