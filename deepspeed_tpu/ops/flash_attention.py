"""Flash attention for TPU in Pallas (fwd + bwd, custom_vjp, GQA-native).

Capability-equivalent of the reference's fused attention kernels
(``csrc/transformer/inference/csrc/softmax.cu`` + context kernels and the
training softmax in ``csrc/transformer/softmax_kernels.cu``), re-designed as a
single online-softmax kernel (the CUDA code materializes the S×S score matrix;
on TPU we never leave VMEM).

Layout: inputs [B, S, N, D] (seq-major like the models), internally
[B, N, S, D]. fp32 accumulation, bf16-friendly.

Blocked-KV grid: the grid has a KV-block dimension (innermost), so only one
[block_k, D] tile of K and V is VMEM-resident at a time and Pallas
double-buffers the next tile's DMA behind the current tile's compute. The
online-softmax state (m, l, acc) is carried across KV steps in VMEM scratch.
Sequence length is therefore bounded by HBM, not VMEM (the previous design
kept the whole [S, D] K/V — and in the backward a [rep, S, D] fp32 block —
resident, capping S at ~8-16k).

Causal masking skips invisible blocks two ways: `pl.when` predication skips
the compute, and the K/V index maps clamp invisible steps to the last visible
block so the pipeline emitter elides their DMAs (same-index fetches are
skipped). Causal attention therefore does ~half the FLOPs and ~half the HBM
traffic of full attention.

GQA is native: when n_q_heads > n_kv_heads the grid runs over KV heads and
each program processes the whole query-head GROUP against one K/V stream —
K/V are never repeated in HBM and their VMEM loads amortize over the group
(the naive path repeats K/V n_q/n_kv times).

Backward uses the standard flash decomposition (dQ kernel + joint dK/dV
kernel) with the forward's log-sum-exp residuals; both are blocked the same
way (dQ: KV innermost with dQ in scratch; dK/dV: Q innermost with dK/dV in
scratch).

``fused_backward=True`` folds the delta epilogue (``rowsum(dO * O)``) into
both backward grids: the kernels read O directly and compute delta on-chip
(dQ grid: once per Q block at the first KV step, held in VMEM scratch;
dK/dV grid: recomputed per step — a [rows, D] elementwise-rowsum, noise
next to the step's five matmuls). This removes the separate XLA delta pass
— a full extra read of dO and O plus the [B, N, S, 1] delta tensor's HBM
round-trip per layer per step — so the whole attention backward is two
Pallas grids with no XLA prologue between forward and backward. The
forward also tags its outputs with ``checkpoint_name`` ("flash_out" /
"flash_lse"): the ``dots_and_attn`` remat policy
(models/transformer._remat_policy) pins them across the fwd/bwd boundary
so the backward does not replay the full online-softmax forward kernel
under layer-level ``jax.checkpoint``.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
# rep * block_q rows of fp32 state live in VMEM scratch; past ~1024 rows the
# m/l/acc scratch plus the double-buffered Q/KV tiles exceed scoped VMEM
# (measured: rows=2048 fails to compile on v5e at D=64).
MAX_ROWS = 1024
NEG_INF = -1e30
# Floor for the running row-max: keeps exp(s - m) == 0 for fully-masked rows
# (otherwise m == s == NEG_INF makes exp(0) == 1 and a dead row attends
# uniformly to its masked keys). Real scores never get near -1e20.
M_FLOOR = -1e20


def _interpret() -> bool:
    """Pallas interpreter on non-TPU backends (CPU tests)."""
    return jax.default_backend() not in ("tpu", "axon")


def _compiler_params(n_parallel: int):
    """Grid semantics: all dims parallel except the innermost (carries
    scratch state / revisits the output block)."""
    if _interpret():
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel",) * n_parallel + ("arbitrary",))


def _pow2_floor(x: int) -> int:
    return 1 << (max(x, 1).bit_length() - 1)


def _pick_blocks(s: int, block_q: int, block_k: int, rep: int = 1):
    # power-of-two blocks: halving then always terminates at a divisor of
    # any s with a pow2 factor (e.g. s % 128 == 0 keeps bk >= 128), instead
    # of degenerating to 1 for non-pow2 requests
    bq = _pow2_floor(min(block_q, s))
    bk = _pow2_floor(min(block_k, s))
    while s % bq:
        bq //= 2
    while s % bk:
        bk //= 2
    while rep * bq > MAX_ROWS and bq > 8:
        bq //= 2
    return max(bq, 1), max(bk, 1)


def _causal_mask(s, q_start, k_start, rows, block_k, block_q):
    """rows = rep*block_q stacked row-major by head; row r is query position
    q_start + (r % block_q)."""
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 0) % block_q
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _block_visible(qi, kj, block_q, block_k):
    """True iff KV block kj intersects the causal triangle of Q block qi
    (i.e. last query row >= first key col)."""
    return (qi + 1) * block_q > kj * block_k


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, lse_ref,
                m_s, l_s, acc_s, *, sm_scale, causal, rep, block_q, block_k):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    num_kv = pl.num_programs(3)
    d = q_ref.shape[-1]
    rows = rep * block_q

    @pl.when(kj == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    visible = _block_visible(qi, kj, block_q, block_k) if causal else True

    @pl.when(visible)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32).reshape(rows, d) * sm_scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi * block_q, kj * block_k, rows, block_k,
                             block_q)
        if m_ref is not None:
            kv_ok = m_ref[0, 0:1, :] > 0
            s = jnp.where(kv_ok, s, NEG_INF)   # [1,bk] broadcasts over rows
        m = m_s[:, 0:1]
        l = l_s[:, 0:1]
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True)),
                            M_FLOOR)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(kj == num_kv - 1)
    def _finalize():
        l = l_s[:, 0:1]
        m = m_s[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[:] / l_safe).reshape(rep, block_q, d).astype(
            o_ref.dtype)
        lse_ref[0, 0] = (m + jnp.log(l_safe)).reshape(rep, block_q, 1)


def _fwd_kernel_nomask(q_ref, k_ref, v_ref, o_ref, lse_ref, *scratch, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref, *scratch, **kw)


def _clamp_kv(i, j, causal, bq, bk):
    """Clamp invisible KV steps to the last visible block: the pipeline
    emitter skips DMAs whose block index equals the previous step's."""
    if causal:
        last_visible = jax.lax.div((i + 1) * bq - 1, bk)
        j = jnp.minimum(j, last_visible)
    return j


def _kv_index_map(causal, bq, bk):
    return lambda b, g, i, j: (b, g, _clamp_kv(i, j, causal, bq, bk), 0)


# The [B, 8, S] key-padding mask is blocked like K/V (Mosaic's lane rule
# requires bk % 128 == 0 for this spec — guaranteed by the wrapper's masked-
# path guard: S % 128 == 0 and block_k >= 128 make _pick_blocks land on a
# multiple of 128).
def _mask_kv_index_map(causal, bq, bk):
    return lambda b, g, i, j: (b, 0, _clamp_kv(i, j, causal, bq, bk))


def _fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k):
    B, N, S, D = q.shape
    Nkv = k.shape[1]
    rep = N // Nkv
    bq, bk = _pick_blocks(S, block_q, block_k, rep)
    grid = (B, Nkv, S // bq, S // bk)
    rows = rep * bq

    kv_spec = pl.BlockSpec((1, 1, bk, D), _kv_index_map(causal, bq, bk),
                           memory_space=pltpu.VMEM)
    kern = _fwd_kernel if kv_mask is not None else _fwd_kernel_nomask
    kernel = functools.partial(kern, sm_scale=sm_scale, causal=causal,
                               rep=rep, block_q=bq, block_k=bk)
    # q viewed as [B, Nkv, rep, S, D]: one program owns the whole head group
    qg = q.reshape(B, Nkv, rep, S, D)
    mask_spec = pl.BlockSpec((1, 8, bk), _mask_kv_index_map(causal, bq, bk),
                             memory_space=pltpu.VMEM)
    extra = () if kv_mask is None else (kv_mask,)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, rep, bq, D),
                         lambda b, g, i, j: (b, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
            kv_spec, kv_spec,
        ] + ([mask_spec] if kv_mask is not None else []),
        out_specs=[
            pl.BlockSpec((1, 1, rep, bq, D),
                         lambda b, g, i, j: (b, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
            # trailing singleton keeps the (sublane, lane) tile legal
            pl.BlockSpec((1, 1, rep, bq, 1),
                         lambda b, g, i, j: (b, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Nkv, rep, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Nkv, rep, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, 128), jnp.float32),   # m (lane-padded)
            pltpu.VMEM((rows, 128), jnp.float32),   # l
            pltpu.VMEM((rows, D), jnp.float32),     # acc
        ],
        compiler_params=_compiler_params(3),
        interpret=_interpret(),
    )(qg, k, v, *extra)
    return o.reshape(B, N, S, D), lse.reshape(B, N, S, 1)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, aux_ref, m_ref,
                   dq_ref, dq_s, *scratch, sm_scale, causal, rep, block_q,
                   block_k, fused=False):
    """aux_ref carries the precomputed delta ([..., 1], unfused) or the
    forward O block ([..., D], fused): the fused grid computes delta =
    rowsum(dO * O) ONCE per Q block at the first KV step and holds it in
    VMEM scratch across the KV sweep — no XLA delta pass, no [B,N,S,1]
    HBM round-trip."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    num_kv = pl.num_programs(3)
    d = q_ref.shape[-1]
    rows = rep * block_q

    @pl.when(kj == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)
        if fused:
            do = do_ref[0, 0].astype(jnp.float32).reshape(rows, d)
            o = aux_ref[0, 0].astype(jnp.float32).reshape(rows, d)
            scratch[0][:] = jnp.broadcast_to(
                jnp.sum(do * o, axis=-1, keepdims=True), scratch[0].shape)

    visible = _block_visible(qi, kj, block_q, block_k) if causal else True

    @pl.when(visible)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32).reshape(rows, d)
        do = do_ref[0, 0].astype(jnp.float32).reshape(rows, d)
        lse = lse_ref[0, 0].reshape(rows, 1)
        delta = (scratch[0][:, 0:1] if fused
                 else aux_ref[0, 0].reshape(rows, 1))
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi * block_q, kj * block_k, rows, block_k,
                             block_q)
        if m_ref is not None:
            kv_ok = m_ref[0, 0:1, :] > 0
            s = jnp.where(kv_ok, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_s[:].reshape(rep, block_q, d).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, aux_ref, m_ref,
                    dk_ref, dv_ref, dk_s, dv_s, *, sm_scale, causal, rep,
                    block_q, block_k, fused=False):
    """aux_ref: precomputed delta (unfused) or the forward O block (fused —
    delta recomputed per (kj, qi) step; a [rows, D] rowsum is noise next to
    the step's five matmuls and saves the separate delta pass)."""
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    num_q = pl.num_programs(3)
    d = k_ref.shape[-1]
    rows = rep * block_q
    k_start = kj * block_k

    @pl.when(qi == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    visible = _block_visible(qi, kj, block_q, block_k) if causal else True

    @pl.when(visible)
    def _step():
        k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        q = q_ref[0, 0].astype(jnp.float32).reshape(rows, d)
        do = do_ref[0, 0].astype(jnp.float32).reshape(rows, d)
        lse = lse_ref[0, 0].reshape(rows, 1)
        if fused:
            o = aux_ref[0, 0].astype(jnp.float32).reshape(rows, d)
            delta = jnp.sum(do * o, axis=-1, keepdims=True)
        else:
            delta = aux_ref[0, 0].reshape(rows, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi * block_q, k_start, rows, block_k, block_q)
        if m_ref is not None:
            kv_ok = m_ref[0, 0:1, :] > 0
            s = jnp.where(kv_ok, s, NEG_INF)
        p = jnp.exp(s - lse)                        # [rows, bk]
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


def _bwd_dq_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, aux_ref,
                          dq_ref, *scratch, **kw):
    _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, aux_ref, None,
                   dq_ref, *scratch, **kw)


def _bwd_dkv_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, aux_ref,
                           dk_ref, dv_ref, *scratch, **kw):
    _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, aux_ref, None,
                    dk_ref, dv_ref, *scratch, **kw)


def _q_index_map(causal, bq, bk):
    """dK/dV kernel (Q innermost): clamp pre-diagonal Q steps up to the first
    visible block so their DMAs are elided."""
    def index(b, g, j, i):
        if causal:
            first_visible = jax.lax.div(j * bk, bq)
            i = jnp.maximum(i, first_visible)
        return (b, g, 0, i, 0)
    return index


def _bwd(sm_scale, causal, block_q, block_k, fused, residuals, g):
    q, k, v, kv_mask, o, lse = residuals
    do = g
    B, N, S, D = q.shape
    Nkv = k.shape[1]
    rep = N // Nkv
    bq, bk = _pick_blocks(S, block_q, block_k, rep)
    rows = rep * bq

    qg = q.reshape(B, Nkv, rep, S, D)
    dog = do.reshape(B, Nkv, rep, S, D)
    lseg = lse.reshape(B, Nkv, rep, S, 1)
    if fused:
        # delta computed inside both grids from O directly — no XLA pass
        auxg = o.reshape(B, Nkv, rep, S, D)
    else:
        # delta = rowsum(dO * O) — a separate XLA pass over dO and O
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)  # [B,N,S,1]
        auxg = delta.reshape(B, Nkv, rep, S, 1)

    # ---- dQ: grid (B, Nkv, num_q, num_kv), KV innermost ----
    kv_blk = pl.BlockSpec((1, 1, bk, D), _kv_index_map(causal, bq, bk),
                          memory_space=pltpu.VMEM)
    grp_blk = pl.BlockSpec((1, 1, rep, bq, D),
                           lambda b, g, i, j: (b, g, 0, i, 0),
                           memory_space=pltpu.VMEM)
    grp_vec = pl.BlockSpec((1, 1, rep, bq, 1),
                           lambda b, g, i, j: (b, g, 0, i, 0),
                           memory_space=pltpu.VMEM)
    mask_kv = pl.BlockSpec((1, 8, bk), _mask_kv_index_map(causal, bq, bk),
                           memory_space=pltpu.VMEM)
    extra = () if kv_mask is None else (kv_mask,)
    aux_blk = grp_blk if fused else grp_vec
    dq_kern = _bwd_dq_kernel if kv_mask is not None else _bwd_dq_kernel_nomask
    dq = pl.pallas_call(
        functools.partial(dq_kern, sm_scale=sm_scale, causal=causal,
                          rep=rep, block_q=bq, block_k=bk, fused=fused),
        grid=(B, Nkv, S // bq, S // bk),
        in_specs=[grp_blk, kv_blk, kv_blk, grp_blk, grp_vec, aux_blk]
        + ([mask_kv] if kv_mask is not None else []),
        out_specs=grp_blk,
        out_shape=jax.ShapeDtypeStruct((B, Nkv, rep, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((rows, D), jnp.float32)]
        + ([pltpu.VMEM((rows, 128), jnp.float32)] if fused else []),
        compiler_params=_compiler_params(3),
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, auxg, *extra)

    # ---- dK/dV: grid (B, Nkv, num_kv, num_q), Q innermost ----
    qmap = _q_index_map(causal, bq, bk)
    grp_q = pl.BlockSpec((1, 1, rep, bq, D), qmap, memory_space=pltpu.VMEM)
    grp_q_vec = pl.BlockSpec((1, 1, rep, bq, 1), qmap,
                             memory_space=pltpu.VMEM)
    kv_out = pl.BlockSpec((1, 1, bk, D), lambda b, g, j, i: (b, g, j, 0),
                          memory_space=pltpu.VMEM)
    mask_out = pl.BlockSpec((1, 8, bk), lambda b, g, j, i: (b, 0, j),
                            memory_space=pltpu.VMEM)
    dkv_kern = (_bwd_dkv_kernel if kv_mask is not None
                else _bwd_dkv_kernel_nomask)
    aux_q = grp_q if fused else grp_q_vec
    dk, dv = pl.pallas_call(
        functools.partial(dkv_kern, sm_scale=sm_scale, causal=causal,
                          rep=rep, block_q=bq, block_k=bk, fused=fused),
        grid=(B, Nkv, S // bk, S // bq),
        in_specs=[grp_q, kv_out, kv_out, grp_q, grp_q_vec, aux_q]
        + ([mask_out] if kv_mask is not None else []),
        out_specs=[kv_out, kv_out],
        out_shape=[
            jax.ShapeDtypeStruct((B, Nkv, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Nkv, S, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=_compiler_params(3),
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, auxg, *extra)
    return dq.reshape(B, N, S, D), dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kv_mask, sm_scale, causal, block_q, block_k, fused):
    o, _ = _fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k, fused):
    o, lse = _fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k)
    # named residuals: when this call sits inside a jax.checkpoint region
    # (the layer scan body), the "dots_and_attn" remat policy saves O and
    # the log-sum-exp across the fwd/bwd boundary — the backward then runs
    # straight into the two backward grids instead of replaying the full
    # online-softmax forward kernel first
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, kv_mask, o, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, fused, residuals, g):
    dq, dk, dv = _bwd(sm_scale, causal, block_q, block_k, fused, residuals,
                      g)
    kv_mask = residuals[3]
    import numpy as _np
    dmask = (None if kv_mask is None
             else _np.zeros(kv_mask.shape, jax.dtypes.float0))
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    kv_mask=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    fused_backward: bool = False):
    """q: [B, S, Nq, D]; k, v: [B, S, Nkv, D] (Nkv may divide Nq: GQA runs
    natively without repeating K/V) -> [B, S, Nq, D].

    kv_mask: optional [B, S] bool/int padding mask over keys — masked
    positions are excluded inside the kernel (no O(S^2) fallback).
    fused_backward: fold the delta epilogue into the backward grids (the
    kernels read O directly; no separate XLA delta pass)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"n_q_heads {q.shape[2]} not divisible by "
                         f"n_kv_heads {k.shape[2]}")
    if kv_mask is not None and not _interpret():
        # the blocked mask spec needs block_k % 128 == 0 on TPU; _pick_blocks
        # halves from a power-of-two >= 128, so any S % 128 == 0 lands there
        if q.shape[1] % 128:
            raise ValueError("kv_mask on TPU requires seq_len % 128 == 0 "
                             f"(got {q.shape[1]})")
        block_k = max(block_k, 128)
    qt = jnp.swapaxes(q, 1, 2)  # [B, N, S, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if kv_mask is not None:
        kv_mask = jnp.asarray(kv_mask).astype(jnp.float32)
        # (B, 8, S): the sublane-broadcast copy satisfies Mosaic's dynamic
        # sublane-index alignment rule (int8 [B,S] rows can't be dynamically
        # indexed); 8x a [B,S] int8 is negligible
        kv_mask = jnp.broadcast_to(kv_mask[:, None, :],
                                   (kv_mask.shape[0], 8, kv_mask.shape[1]))
    o = _flash(qt, kt, vt, kv_mask, float(sm_scale), bool(causal), block_q,
               block_k, bool(fused_backward))
    return jnp.swapaxes(o, 1, 2)


def reference_attention(q, k, v, *, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """XLA reference for parity tests (handles GQA by repeat)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    B, S, N, D = q.shape
    if k.shape[2] != N:
        rep = N // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bsnd,btnd->bnst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnst,btnd->bsnd", p, v.astype(jnp.float32)).astype(q.dtype)
