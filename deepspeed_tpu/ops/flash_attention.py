"""Flash attention for TPU in Pallas (fwd + bwd, custom_vjp, GQA-native).

Capability-equivalent of the reference's fused attention kernels
(``csrc/transformer/inference/csrc/softmax.cu`` + context kernels and the
training softmax in ``csrc/transformer/softmax_kernels.cu``), re-designed as a
single online-softmax kernel (the CUDA code materializes the S×S score matrix;
on TPU we never leave VMEM).

Layout: inputs [B, S, N, D] (seq-major like the models), internally
[B, N, S, D]. fp32 accumulation, bf16-friendly. Causal masking is computed
with block-level early-out: fully-masked K blocks are skipped, so causal
attention does ~half the FLOPs of full.

GQA is native: when n_q_heads > n_kv_heads the grid runs over KV heads and
each program processes the whole query-head GROUP against one K/V stream —
K/V are never repeated in HBM and their VMEM loads amortize over the group
(the naive path repeats K/V n_q/n_kv times).

Backward uses the standard flash decomposition (dQ kernel + joint dK/dV
kernel) with the forward's log-sum-exp residuals.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def _interpret() -> bool:
    """Pallas interpreter on non-TPU backends (CPU tests)."""
    return jax.default_backend() not in ("tpu", "axon")


def _pick_blocks(s: int, block_q: int, block_k: int):
    bq = min(block_q, s)
    bk = min(block_k, s)
    while s % bq:
        bq //= 2
    while s % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


def _causal_mask(s, q_start, k_start, rows, block_k, block_q):
    """rows = rep*block_q stacked row-major by head; row r is query position
    q_start + (r % block_q)."""
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 0) % block_q
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, lse_ref, *, sm_scale,
                causal, rep, block_q, block_k, seq_len):
    qi = pl.program_id(2)
    d = q_ref.shape[-1]
    rows = rep * block_q
    q = q_ref[0, 0].astype(jnp.float32).reshape(rows, d) * sm_scale
    num_kv = seq_len // block_k

    m0 = jnp.full((rows, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows, 1), jnp.float32)
    acc0 = jnp.zeros((rows, d), jnp.float32)

    q_start = qi * block_q

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, j * block_k, rows, block_k, block_q)
        if m_ref is not None:
            kv_ok = m_ref[0, 0:1, pl.ds(j * block_k, block_k)] > 0
            s = jnp.where(kv_ok, s, NEG_INF)   # [1,bk] broadcasts over rows
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only K blocks with k_start <= q_end participate (block early-out)
        num_visible = jnp.minimum((q_start + block_q + block_k - 1) // block_k, num_kv)
    else:
        num_visible = num_kv
    m, l, acc = jax.lax.fori_loop(0, num_visible, body, (m0, l0, acc0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).reshape(rep, block_q, d).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l_safe)).reshape(rep, block_q, 1)


def _fwd_kernel_nomask(q_ref, k_ref, v_ref, o_ref, lse_ref, **kw):
    _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref, **kw)


def _fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k):
    B, N, S, D = q.shape
    Nkv = k.shape[1]
    rep = N // Nkv
    bq, bk = _pick_blocks(S, block_q, block_k)
    grid = (B, Nkv, S // bq)

    kv_spec = pl.BlockSpec((1, 1, S, D), lambda b, g, i: (b, g, 0, 0),
                           memory_space=pltpu.VMEM)
    kern = _fwd_kernel if kv_mask is not None else _fwd_kernel_nomask
    kernel = functools.partial(kern, sm_scale=sm_scale, causal=causal,
                               rep=rep, block_q=bq, block_k=bk, seq_len=S)
    # q viewed as [B, Nkv, rep, S, D]: one program owns the whole head group
    qg = q.reshape(B, Nkv, rep, S, D)
    mask_spec = pl.BlockSpec((1, 8, S), lambda b, g, i: (b, 0, 0),
                             memory_space=pltpu.VMEM)
    extra = () if kv_mask is None else (kv_mask,)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, rep, bq, D), lambda b, g, i: (b, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
            kv_spec, kv_spec,
        ] + ([mask_spec] if kv_mask is not None else []),
        out_specs=[
            pl.BlockSpec((1, 1, rep, bq, D), lambda b, g, i: (b, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
            # trailing singleton keeps the (sublane, lane) tile legal
            pl.BlockSpec((1, 1, rep, bq, 1), lambda b, g, i: (b, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Nkv, rep, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Nkv, rep, S, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(qg, k, v, *extra)
    return o.reshape(B, N, S, D), lse.reshape(B, N, S, 1)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, m_ref,
                   dq_ref, *, sm_scale, causal, rep, block_q, block_k,
                   seq_len):
    qi = pl.program_id(2)
    q_start = qi * block_q
    d = q_ref.shape[-1]
    rows = rep * block_q
    q = q_ref[0, 0].astype(jnp.float32).reshape(rows, d)
    do = do_ref[0, 0].astype(jnp.float32).reshape(rows, d)
    lse = lse_ref[0, 0].reshape(rows, 1)
    delta = delta_ref[0, 0].reshape(rows, 1)
    num_kv = seq_len // block_k

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, q_start, j * block_k, rows, block_k, block_q)
        if m_ref is not None:
            kv_ok = m_ref[0, 0:1, pl.ds(j * block_k, block_k)] > 0
            s = jnp.where(kv_ok, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    if causal:
        num_visible = jnp.minimum((q_start + block_q + block_k - 1) // block_k, num_kv)
    else:
        num_visible = num_kv
    dq = jax.lax.fori_loop(0, num_visible, body,
                           jnp.zeros((rows, d), jnp.float32))
    dq_ref[0, 0] = dq.reshape(rep, block_q, d).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, m_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, rep, block_q,
                    block_k, seq_len):
    ki = pl.program_id(2)
    bi = pl.program_id(0)
    k = k_ref[0, 0].astype(jnp.float32)            # [bk, d]
    v = v_ref[0, 0].astype(jnp.float32)
    d = k.shape[-1]
    num_q = seq_len // block_q
    k_start = ki * block_k
    rows = rep * block_q

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, :, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32).reshape(rows, d)
        do = do_ref[0, 0, :, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32).reshape(rows, d)
        lse = lse_ref[0, 0, :, pl.ds(i * block_q, block_q), :].reshape(rows, 1)
        delta = delta_ref[0, 0, :, pl.ds(i * block_q, block_q), :].reshape(
            rows, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, i * block_q, k_start, rows, block_k, block_q)
        if m_ref is not None:
            kv_ok = m_ref[0, 0:1, pl.ds(k_start, block_k)] > 0
            s = jnp.where(kv_ok, s, NEG_INF)
        p = jnp.exp(s - lse)                        # [rows, bk]
        dv_new = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_new = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    if causal:
        # q blocks at positions >= k_start participate
        first_q = k_start // block_q
    else:
        first_q = 0
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, **kw):
    _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                   dq_ref, **kw)


def _bwd_dkv_kernel_nomask(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, **kw):
    _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, None,
                    dk_ref, dv_ref, **kw)


def _bwd(sm_scale, causal, block_q, block_k, residuals, g):
    q, k, v, kv_mask, o, lse = residuals
    do = g
    B, N, S, D = q.shape
    Nkv = k.shape[1]
    rep = N // Nkv
    bq, bk = _pick_blocks(S, block_q, block_k)

    # delta = rowsum(dO * O) — cheap, let XLA fuse it
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B,N,S,1]

    qg = q.reshape(B, Nkv, rep, S, D)
    dog = do.reshape(B, Nkv, rep, S, D)
    lseg = lse.reshape(B, Nkv, rep, S, 1)
    deltag = delta.reshape(B, Nkv, rep, S, 1)

    kv_full = pl.BlockSpec((1, 1, S, D), lambda b, g, i: (b, g, 0, 0),
                           memory_space=pltpu.VMEM)
    grp_blk = pl.BlockSpec((1, 1, rep, bq, D), lambda b, g, i: (b, g, 0, i, 0),
                           memory_space=pltpu.VMEM)
    grp_vec = pl.BlockSpec((1, 1, rep, bq, 1), lambda b, g, i: (b, g, 0, i, 0),
                           memory_space=pltpu.VMEM)
    grp_full = pl.BlockSpec((1, 1, rep, S, D), lambda b, g, i: (b, g, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    grp_full_vec = pl.BlockSpec((1, 1, rep, S, 1),
                                lambda b, g, i: (b, g, 0, 0, 0),
                                memory_space=pltpu.VMEM)

    mask_spec = pl.BlockSpec((1, 8, S), lambda b, g, i: (b, 0, 0),
                             memory_space=pltpu.VMEM)
    extra = () if kv_mask is None else (kv_mask,)
    dq_kern = _bwd_dq_kernel if kv_mask is not None else _bwd_dq_kernel_nomask
    dq = pl.pallas_call(
        functools.partial(dq_kern, sm_scale=sm_scale, causal=causal,
                          rep=rep, block_q=bq, block_k=bk, seq_len=S),
        grid=(B, Nkv, S // bq),
        in_specs=[grp_blk, kv_full, kv_full, grp_blk, grp_vec, grp_vec]
        + ([mask_spec] if kv_mask is not None else []),
        out_specs=grp_blk,
        out_shape=jax.ShapeDtypeStruct((B, Nkv, rep, S, D), q.dtype),
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, deltag, *extra)

    kv_blk = pl.BlockSpec((1, 1, bk, D), lambda b, g, i: (b, g, i, 0),
                          memory_space=pltpu.VMEM)
    dkv_kern = (_bwd_dkv_kernel if kv_mask is not None
                else _bwd_dkv_kernel_nomask)
    dk, dv = pl.pallas_call(
        functools.partial(dkv_kern, sm_scale=sm_scale, causal=causal,
                          rep=rep, block_q=bq, block_k=bk, seq_len=S),
        grid=(B, Nkv, S // bk),
        in_specs=[grp_full, kv_blk, kv_blk, grp_full, grp_full_vec,
                  grp_full_vec]
        + ([mask_spec] if kv_mask is not None else []),
        out_specs=[kv_blk, kv_blk],
        out_shape=[
            jax.ShapeDtypeStruct((B, Nkv, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Nkv, S, D), q.dtype),
        ],
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, deltag, *extra)
    return dq.reshape(B, N, S, D), dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, kv_mask, sm_scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k)
    return o, (q, k, v, kv_mask, o, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, residuals, g):
    dq, dk, dv = _bwd(sm_scale, causal, block_q, block_k, residuals, g)
    kv_mask = residuals[3]
    import numpy as _np
    dmask = (None if kv_mask is None
             else _np.zeros(kv_mask.shape, jax.dtypes.float0))
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    kv_mask=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """q: [B, S, Nq, D]; k, v: [B, S, Nkv, D] (Nkv may divide Nq: GQA runs
    natively without repeating K/V) -> [B, S, Nq, D].

    kv_mask: optional [B, S] bool/int padding mask over keys — masked
    positions are excluded inside the kernel (no O(S^2) fallback)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"n_q_heads {q.shape[2]} not divisible by "
                         f"n_kv_heads {k.shape[2]}")
    qt = jnp.swapaxes(q, 1, 2)  # [B, N, S, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if kv_mask is not None:
        kv_mask = jnp.asarray(kv_mask).astype(jnp.float32)
        # (B, 8, S): the sublane-broadcast copy satisfies Mosaic's dynamic
        # sublane-index alignment rule (int8 [B,S] rows can't be dynamically
        # indexed); 8x a [B,S] int8 is negligible
        kv_mask = jnp.broadcast_to(kv_mask[:, None, :],
                                   (kv_mask.shape[0], 8, kv_mask.shape[1]))
    o = _flash(qt, kt, vt, kv_mask, float(sm_scale), bool(causal), block_q,
               block_k)
    return jnp.swapaxes(o, 1, 2)


def reference_attention(q, k, v, *, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """XLA reference for parity tests (handles GQA by repeat)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    B, S, N, D = q.shape
    if k.shape[2] != N:
        rep = N // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bsnd,btnd->bnst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnst,btnd->bsnd", p, v.astype(jnp.float32)).astype(q.dtype)
