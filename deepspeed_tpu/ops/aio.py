"""Python binding for the native async-IO library (ctypes).

Reference: ``csrc/aio/py_lib/py_ds_aio.cpp:12-44`` (`aio_handle` with
sync/async pread/pwrite) + ``op_builder`` JIT build. We compile the C++ on
first use with g++ (no torch extension machinery needed) and cache the .so
next to the source.
"""

import ctypes
import errno
import hashlib
import os
import subprocess
from typing import Optional

import numpy as np

from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.retry import retry_io
from deepspeed_tpu.utils.logging import logger

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "aio", "dstpu_aio.cpp")

_LIB = None


def _cache_dir() -> str:
    base = os.environ.get("DSTPU_CACHE_DIR") or os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "deepspeed_tpu")
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> Optional[str]:
    # Key the cached .so by source hash (never by mtime): a stale or
    # pre-committed binary must never shadow the audited source.
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_cache_dir(), f"libdstpu_aio-{digest}.so")
    if os.path.exists(so):
        return so
    tmp = f"{so}.tmp.{os.getpid()}"  # per-process: concurrent builds must not race
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except Exception as e:
        logger.warning(f"aio build failed: {e}")
        return None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    so = _build()
    if so is None:
        return None
    lib = ctypes.CDLL(so)
    lib.dstpu_aio_open.restype = ctypes.c_void_p
    lib.dstpu_aio_open.argtypes = [ctypes.c_uint, ctypes.c_uint, ctypes.c_int]
    lib.dstpu_aio_close.argtypes = [ctypes.c_void_p]
    lib.dstpu_aio_uses_uring.argtypes = [ctypes.c_void_p]
    lib.dstpu_aio_uses_uring.restype = ctypes.c_int
    for fn in (lib.dstpu_aio_pread, lib.dstpu_aio_pwrite):
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                       ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
    lib.dstpu_aio_alloc.restype = ctypes.c_void_p
    lib.dstpu_aio_alloc.argtypes = [ctypes.c_int64]
    lib.dstpu_aio_free.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def aio_available() -> bool:
    return _load() is not None


def report_fallback(component: str, reason: str = "native build "
                    "unavailable") -> None:
    """Surface an aio-unavailable fallback as a STRUCTURED event
    (``aio_fallback``) through the robustness event stream — the monitor
    drains it at the next window boundary, so an offload tier silently
    running on synchronous numpy file IO is visible in the telemetry
    JSONL, not just a one-time log line."""
    from deepspeed_tpu.robustness import events
    events.emit("aio_fallback", component=component, reason=str(reason))


# the handle's own proven defaults (deeper/wider than the reference's
# conservative AIOConfig constants of 8/1)
_DEFAULT_QUEUE_DEPTH = 32
_DEFAULT_THREAD_COUNT = 4


class AIOHandle:
    """Reference: ``aio_handle``. block_size/queue_depth/thread_count map to
    the same-named config keys (AIOConfig)."""

    @classmethod
    def from_config(cls, aio_cfg=None, role: str = "read") -> "AIOHandle":
        """Build a handle from the config ``aio`` section. ``role`` picks
        the read- or write-side queue depth: the offload pipelines open one
        ring per direction so prefetch reads never queue behind write-behind
        (read_queue_depth/write_queue_depth default to queue_depth).

        The AIOConfig dataclass defaults mirror the reference constants
        (queue_depth 8, thread_count 1), but this handle's own proven
        defaults are 32/4 — fields the user did NOT set in their config
        keep the handle defaults, so wiring the config section through
        never silently downgrades a default-config run's IO parallelism."""
        if aio_cfg is None:
            return cls()
        was_set = getattr(aio_cfg, "was_set", lambda _k: True)
        depth = (aio_cfg.read_queue_depth if role == "read"
                 else aio_cfg.write_queue_depth)
        if depth is None:
            depth = (aio_cfg.queue_depth if was_set("queue_depth")
                     else _DEFAULT_QUEUE_DEPTH)
        threads = (aio_cfg.thread_count if was_set("thread_count")
                   else _DEFAULT_THREAD_COUNT)
        return cls(block_size=aio_cfg.block_size, queue_depth=depth,
                   thread_count=threads)

    def __init__(self, block_size: int = 1 << 20,
                 queue_depth: int = _DEFAULT_QUEUE_DEPTH,
                 thread_count: int = _DEFAULT_THREAD_COUNT):
        lib = _load()
        if lib is None:
            raise RuntimeError("native aio library unavailable (g++ build failed)")
        self._lib = lib
        self._h = lib.dstpu_aio_open(block_size, queue_depth, thread_count)
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count

    @property
    def uses_io_uring(self) -> bool:
        return bool(self._lib.dstpu_aio_uses_uring(self._h))

    def pwrite(self, path: str, array: np.ndarray, file_offset: int = 0,
               direct: bool = False) -> None:
        # bounded retry: a transient EIO/EAGAIN from the ring is retried
        # with backoff; the terminal error names file, offset and attempt
        # count (robustness/retry.py) instead of an anonymous IOError
        arr = np.ascontiguousarray(array)

        def do():
            rb_faults.io_seam("aio_write", path, file_offset)
            rc = self._lib.dstpu_aio_pwrite(
                self._h, path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
                arr.nbytes, file_offset, int(direct))
            if rc != 0:
                raise OSError(errno.EIO, f"aio pwrite rc={rc}")
        retry_io(do, what="aio pwrite", path=path, offset=file_offset)

    def pread(self, path: str, shape, dtype, file_offset: int = 0,
              direct: bool = False, out: Optional[np.ndarray] = None) -> np.ndarray:
        arr = out if out is not None else np.empty(shape, dtype)

        def do():
            rb_faults.io_seam("aio_read", path, file_offset)
            rc = self._lib.dstpu_aio_pread(
                self._h, path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
                arr.nbytes, file_offset, int(direct))
            if rc != 0:
                raise OSError(errno.EIO, f"aio pread rc={rc}")
            return arr
        return retry_io(do, what="aio pread", path=path, offset=file_offset)

    def close(self):
        # guard with getattr: when _load()/__init__ failed mid-init the
        # instance has no _h/_lib, and __del__ still runs on it — close()
        # must be a no-op there, not an AttributeError (which would surface
        # as "Exception ignored in: __del__" noise at interpreter shutdown)
        h = getattr(self, "_h", None)
        if h and getattr(self, "_lib", None) is not None:
            self._lib.dstpu_aio_close(h)
        self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
