"""LAMB (layer-wise adaptive moments) optimizer.

Reference: ``csrc/lamb/fused_lamb_cuda{.cpp,_kernel.cu}`` + ``ops/lamb``;
1-bit LAMB at ``runtime/fp16/onebit/lamb.py:12``. The CUDA version hand-fuses
the two per-tensor reductions (weight norm, update norm); under XLA the
reductions fuse into the same pass naturally.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizers import (
    Optimizer, ScalarOrSchedule, _lr_at, _master_init, _resolve_master,
    _writeback, cast_tree,
)


def lamb(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999), eps: float = 1e-6,
         weight_decay: float = 0.0, min_trust: float = 0.01,
         max_trust: float = 10.0, use_master_weights: bool = True) -> Optimizer:
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return {
            "step": jnp.zeros((1,), jnp.int32),
            "exp_avg": jax.tree.map(zeros, params),
            "exp_avg_sq": jax.tree.map(zeros, params),
            "master": _master_init(params, use_master_weights),
        }

    def update(grads, state, params):
        step = state["step"] + 1
        lr_t = _lr_at(lr, step)
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["exp_avg"], g32)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["exp_avg_sq"], g32)
        c1 = 1 - b1 ** step.astype(jnp.float32)
        c2 = 1 - b2 ** step.astype(jnp.float32)

        def step_fn(p, m_, v_):
            upd = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
            if weight_decay:
                upd = upd + weight_decay * p
            w_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(upd.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_trust, max_trust),
                1.0)
            return p - lr_t * trust * upd

        new_master = jax.tree.map(step_fn, master, m, v)
        new_params, new_master = _writeback(new_master, params, state.get("master"))
        return new_params, {"step": step, "exp_avg": m, "exp_avg_sq": v,
                            "master": new_master}

    return Optimizer(init, update)
