"""1-bit / communication-efficient optimizers.

Reference: ``deepspeed/runtime/fp16/onebit/adam.py:11`` (OnebitAdam),
``onebit/lamb.py:12`` (OnebitLamb), ``onebit/zoadam.py:11`` (ZeroOneAdam),
with the compressed collective from ``runtime/comm/nccl.py:53``.

TPU-native structure: the reference interleaves Python-side MPI/NCCL calls
with CUDA kernels per step. Here each optimizer is a *phased* pure transform:
the engine (which owns the host-side step counter) selects the phase and runs
the matching jitted program — dense warmup programs contain a dense `pmean`,
compressed programs contain ONLY the 1-bit packed `all_gather`
(comm/compressed.py), and 0/1-Adam "local" programs contain no collective at
all. Phase dispatch never traces a collective under a conditional, which XLA
forbids.

Rank-varying state (the per-worker error-feedback buffers, and 0/1-Adam's
local momentum) carries a leading [dp] axis sharded over the data axis of
the mesh — explicit, checkpointable, and zero extra memory vs replication.
"""

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.compressed import compressed_allreduce_1bit
from deepspeed_tpu.ops.optimizers import (
    Optimizer, ScalarOrSchedule, _lr_at, _master_init, _resolve_master,
    _writeback, cast_tree,
)


class PhasedOptimizer(NamedTuple):
    """Optimizer with per-phase update programs for the engine's compressed
    (shard_map) step path, plus a dense single-program fallback."""
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Any]          # dense GSPMD fallback
    update_phase: Callable[..., Any]  # (grads, state, params, phase, axis)
    phase_for: Callable[[int], str]                  # host step -> phase name
    rank_varying: Tuple[str, ...]                    # state keys w/ [dp] lead


def _pmean_tree(tree, axis):
    if axis is None:
        return tree
    from deepspeed_tpu.comm.comm import comms_logger
    nbytes = sum(int(a.size) * a.dtype.itemsize for a in jax.tree.leaves(tree))
    comms_logger.record("pmean_dense", axis, nbytes)
    return jax.tree.map(lambda g: lax.pmean(g, axis), tree)


def _compress_tree(m_tree, err_tree, axis):
    """corrected = m + err; sync mean(sign*scale) over `axis`; new local
    error = corrected - LOCAL compressed value (reference error feedback)."""
    def one(m_, e_):
        corrected = m_ + e_
        scale = jnp.mean(jnp.abs(corrected))
        # MUST match pack_signs' convention (bit=1 for x>=0): jnp.sign maps
        # 0 -> 0, which would leave a permanent +scale bias on exactly-zero
        # entries that the error feedback never sees
        local_comp = jnp.where(corrected >= 0, scale, -scale)
        if axis is None:
            synced = local_comp
        else:
            synced = compressed_allreduce_1bit(corrected, axis)
        return synced, corrected - local_comp

    out = jax.tree.map(one, m_tree, err_tree)
    synced = jax.tree.map(lambda t: t[0], out,
                          is_leaf=lambda x: isinstance(x, tuple))
    err = jax.tree.map(lambda t: t[1], out,
                       is_leaf=lambda x: isinstance(x, tuple))
    return synced, err


def onebit_adam(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999),
                eps: float = 1e-8, weight_decay: float = 0.0,
                freeze_step: int = 100,
                use_master_weights: bool = True) -> PhasedOptimizer:
    """1-bit Adam: dense Adam for `freeze_step` steps, then the variance
    freezes and the momentum is communicated sign-compressed with error
    feedback (reference ``onebit/adam.py:11``)."""
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
        return {
            "step": jnp.zeros((1,), jnp.int32),
            "exp_avg": jax.tree.map(zeros, params),
            "exp_avg_sq": jax.tree.map(zeros, params),
            "error": jax.tree.map(zeros, params),
            "master": _master_init(params, use_master_weights),
        }

    def _apply(master, m, v, step, params, state):
        lr_t = _lr_at(lr, step)
        c1 = 1 - b1 ** step.astype(jnp.float32)
        c2 = 1 - b2 ** step.astype(jnp.float32)

        def step_fn(p, m_, v_):
            return p - lr_t * (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)

        new_master = jax.tree.map(step_fn, master, m, v)
        return _writeback(new_master, params, state.get("master"))

    def update_phase(grads, state, params, *, phase: str,
                     axis: Optional[str] = None):
        step = state["step"] + 1
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        if weight_decay:
            # COUPLED decay, applied before momentum/compression: the decay
            # term rides the 1-bit stream (reference onebit/adam.py does the
            # same; decoupled decay would silently change trajectories)
            g32 = jax.tree.map(lambda g, p: g + weight_decay * p, g32, master)
        if phase == "warm":
            g32 = _pmean_tree(g32, axis)
            m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                             state["exp_avg"], g32)
            v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                             state["exp_avg_sq"], g32)
            err = state["error"]
        else:  # compressed: local momentum -> 1-bit sync; v frozen
            m_local = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                                   state["exp_avg"], g32)
            m, err = _compress_tree(m_local, state["error"], axis)
            v = state["exp_avg_sq"]
        new_params, new_master = _apply(master, m, v, step, params, state)
        return new_params, {"step": step, "exp_avg": m, "exp_avg_sq": v,
                            "error": err, "master": new_master}

    def update(grads, state, params):
        """Single-program fallback (grads already dense-reduced by GSPMD):
        jnp.where-selects between warm and compressed behavior."""
        warm = (state["step"][0] + 1) <= freeze_step
        pw, sw = update_phase(grads, state, params, phase="warm", axis=None)
        pc, sc = update_phase(grads, state, params, phase="comp", axis=None)
        sel = lambda a, b: jnp.where(warm, a, b)  # noqa: E731
        return (jax.tree.map(sel, pw, pc), jax.tree.map(sel, sw, sc))

    return PhasedOptimizer(
        init=init, update=update, update_phase=update_phase,
        phase_for=lambda step: "warm" if step < freeze_step else "comp",
        rank_varying=("error",))


def onebit_lamb(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999),
                eps: float = 1e-6, weight_decay: float = 0.0,
                freeze_step: int = 100, min_trust: float = 0.01,
                max_trust: float = 10.0,
                use_master_weights: bool = True) -> PhasedOptimizer:
    """1-bit LAMB (reference ``onebit/lamb.py:12``): LAMB warmup capturing
    per-tensor trust ratios; after the freeze the momentum goes 1-bit and the
    FROZEN trust ratios scale the update (the reference freezes its lamb
    coefficients the same way, since post-compression norms are unreliable)."""
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
        return {
            "step": jnp.zeros((1,), jnp.int32),
            "exp_avg": jax.tree.map(zeros, params),
            "exp_avg_sq": jax.tree.map(zeros, params),
            "error": jax.tree.map(zeros, params),
            "frozen_ratio": jax.tree.map(
                lambda p: jnp.ones((), jnp.float32), params),
            "master": _master_init(params, use_master_weights),
        }

    def update_phase(grads, state, params, *, phase: str,
                     axis: Optional[str] = None):
        step = state["step"] + 1
        lr_t = _lr_at(lr, step)
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        c1 = 1 - b1 ** step.astype(jnp.float32)
        c2 = 1 - b2 ** step.astype(jnp.float32)

        if phase == "warm":
            g32 = _pmean_tree(g32, axis)
            m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                             state["exp_avg"], g32)
            v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                             state["exp_avg_sq"], g32)
            err = state["error"]

            def step_fn(p, m_, v_):
                upd = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
                if weight_decay:
                    upd = upd + weight_decay * p
                w_norm = jnp.linalg.norm(p.reshape(-1))
                u_norm = jnp.linalg.norm(upd.reshape(-1))
                trust = jnp.where((w_norm > 0) & (u_norm > 0),
                                  jnp.clip(w_norm / u_norm, min_trust,
                                           max_trust), 1.0)
                return p - lr_t * trust * upd, trust

            out = jax.tree.map(step_fn, master, m, v)
            new_master = jax.tree.map(lambda t: t[0], out,
                                      is_leaf=lambda x: isinstance(x, tuple))
            ratio = jax.tree.map(lambda t: t[1], out,
                                 is_leaf=lambda x: isinstance(x, tuple))
        else:
            m_local = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                                   state["exp_avg"], g32)
            m, err = _compress_tree(m_local, state["error"], axis)
            v = state["exp_avg_sq"]
            ratio = state["frozen_ratio"]

            def step_fn(p, m_, v_, r):
                upd = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
                if weight_decay:
                    upd = upd + weight_decay * p
                return p - lr_t * r * upd

            new_master = jax.tree.map(step_fn, master, m, v, ratio)
        new_params, new_master = _writeback(new_master, params,
                                            state.get("master"))
        return new_params, {"step": step, "exp_avg": m, "exp_avg_sq": v,
                            "error": err, "frozen_ratio": ratio,
                            "master": new_master}

    def update(grads, state, params):
        warm = (state["step"][0] + 1) <= freeze_step
        pw, sw = update_phase(grads, state, params, phase="warm", axis=None)
        pc, sc = update_phase(grads, state, params, phase="comp", axis=None)
        sel = lambda a, b: jnp.where(warm, a, b)  # noqa: E731
        return (jax.tree.map(sel, pw, pc), jax.tree.map(sel, sw, sc))

    return PhasedOptimizer(
        init=init, update=update, update_phase=update_phase,
        phase_for=lambda step: "warm" if step < freeze_step else "comp",
        rank_varying=("error",))


def zero_one_adam(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999),
                  eps: float = 1e-8, weight_decay: float = 0.0,
                  var_freeze_step: int = 100, local_step_scaler: int = 100,
                  local_step_clipper: int = 16,
                  use_master_weights: bool = True) -> PhasedOptimizer:
    """0/1 Adam (reference ``onebit/zoadam.py:11``): variance freezing plus
    *local steps* — after the freeze, workers only synchronize every k-th
    step (k doubling every `local_step_scaler` steps up to
    `local_step_clipper`), and the sync itself is 1-bit compressed.

    TPU adaptation (documented divergence): the reference lets parameters
    drift between syncs and reconciles them; under SPMD the parameters must
    stay bit-identical across data ranks, so local steps here accumulate
    momentum from local gradients WITHOUT touching the parameters, and each
    sync applies the (interval-scaled) update once. Same wire profile, same
    variance-freeze schedule, sync-consistent parameters.
    """
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
        return {
            "step": jnp.zeros((1,), jnp.int32),
            "local_steps": jnp.zeros((1,), jnp.int32),
            "exp_avg": jax.tree.map(zeros, params),
            "exp_avg_sq": jax.tree.map(zeros, params),
            "error": jax.tree.map(zeros, params),
            "master": _master_init(params, use_master_weights),
        }

    def interval_for(step: int) -> int:
        if step < var_freeze_step:
            return 1
        k = 2 ** ((step - var_freeze_step) // max(1, local_step_scaler))
        return min(int(k), local_step_clipper)

    def _apply(master, m, v, step, params, state, scale=1.0):
        lr_t = _lr_at(lr, step)
        c1 = 1 - b1 ** step.astype(jnp.float32)
        c2 = 1 - b2 ** step.astype(jnp.float32)

        def step_fn(p, m_, v_):
            return p - lr_t * scale * (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)

        new_master = jax.tree.map(step_fn, master, m, v)
        return _writeback(new_master, params, state.get("master"))

    def update_phase(grads, state, params, *, phase: str,
                     axis: Optional[str] = None):
        step = state["step"] + 1
        master = _resolve_master(params, state.get("master"))
        g32 = cast_tree(grads, jnp.float32)
        if weight_decay:
            # coupled decay before momentum/compression (see onebit_adam)
            g32 = jax.tree.map(lambda g, p: g + weight_decay * p, g32, master)
        local_steps = state["local_steps"]
        if phase == "dense":
            g32 = _pmean_tree(g32, axis)
            m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                             state["exp_avg"], g32)
            v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                             state["exp_avg_sq"], g32)
            err = state["error"]
            new_params, new_master = _apply(master, m, v, step, params, state)
            local_steps = jnp.zeros_like(local_steps)
        elif phase == "local":
            # accumulate momentum from local grads; params untouched; NO
            # collective in this program at all
            m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                             state["exp_avg"], g32)
            v, err = state["exp_avg_sq"], state["error"]
            new_params, new_master = params, state.get("master")
            local_steps = local_steps + 1
        else:  # "sync": 1-bit momentum sync + interval-scaled update
            m_local = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                                   state["exp_avg"], g32)
            m, err = _compress_tree(m_local, state["error"], axis)
            v = state["exp_avg_sq"]
            k = (local_steps + 1).astype(jnp.float32)[0]
            new_params, new_master = _apply(master, m, v, step, params, state,
                                            scale=k)
            local_steps = jnp.zeros_like(local_steps)
        return new_params, {"step": step, "local_steps": local_steps,
                            "exp_avg": m, "exp_avg_sq": v, "error": err,
                            "master": new_master}

    def phase_for(step: int) -> str:
        if step < var_freeze_step:
            return "dense"
        k = interval_for(step)
        return "sync" if (step - var_freeze_step) % k == k - 1 else "local"

    def update(grads, state, params):
        """Dense fallback: variance freeze only (no local steps — grads are
        already globally reduced, so skipping syncs would skip real work)."""
        warm = (state["step"][0] + 1) <= var_freeze_step
        pd, sd = update_phase(grads, state, params, phase="dense", axis=None)
        ps, ss = update_phase(grads, state, params, phase="sync", axis=None)
        sel = lambda a, b: jnp.where(warm, a, b)  # noqa: E731
        return (jax.tree.map(sel, pd, ps), jax.tree.map(sel, sd, ss))

    return PhasedOptimizer(
        init=init, update=update, update_phase=update_phase,
        phase_for=phase_for,
        rank_varying=("exp_avg", "error"))
