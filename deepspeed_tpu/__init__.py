"""deepspeed_tpu — a TPU-native training & inference framework.

Capability-equivalent to DeepSpeed (reference v0.8.3, see SURVEY.md), re-designed
for JAX/XLA on TPU: GSPMD/pjit sharding over a named device mesh replaces the
hook-and-stream ZeRO runtime; `jax.lax` collectives over ICI/DCN replace NCCL;
Pallas kernels replace CUDA ops; pytrees replace flatten/unflatten.

Public API (mirrors the reference surface, `deepspeed/__init__.py:52,214`):

    engine, optimizer, _, lr_scheduler = deepspeed_tpu.initialize(
        model=model, config=config_dict_or_path)
    inference_engine = deepspeed_tpu.init_inference(model, config=...)
"""

__version__ = "0.1.0"
version = __version__

from deepspeed_tpu.accelerator import get_accelerator, set_accelerator
from deepspeed_tpu.config import Config
from deepspeed_tpu.runtime.engine import Engine, initialize
from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.inference.serving import ServingEngine, init_serving
from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
from deepspeed_tpu import comm
from deepspeed_tpu.utils import logging as _logging

logger = _logging.logger


def add_config_arguments(parser):
    """Add framework arguments to an argparse parser.

    Reference: ``deepspeed/__init__.py:150`` (``_add_core_arguments``) — the
    reference exposes only ``--deepspeed``, ``--deepspeed_config``,
    ``--local_rank``; we expose the equivalent trio.
    """
    group = parser.add_argument_group("deepspeed_tpu", "TPU framework configuration")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable the deepspeed_tpu engine.")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the JSON config file.")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="Local process rank (set by the launcher).")
    return parser
