"""Autotuner: search {mesh shape, ZeRO stage, microbatching, remat policy}.

Reference: ``deepspeed/autotuning/autotuner.py:39`` (Autotuner — builds an
experiment space from the DS config, launches each candidate as a subprocess
via the scheduler, ranks by throughput/latency, writes results dirs) plus its
``tuner/{GridSearchTuner,RandomTuner,ModelBasedTuner}``.

TPU-native re-design: no subprocess launcher — XLA compiles + runs each
candidate in-process (a failed/OOM candidate just scores -inf), and mesh
shape × remat policy matter MORE than on GPU (the SPMD partitioner realizes
a different program per mesh). The search space is the cross product of
  - mesh factorizations of the device count over (data, fsdp, tensor),
  - ZeRO stage (0/1 for replicated-param meshes, 3 for fsdp meshes),
  - gradient-accumulation depth (microbatch sizes),
  - remat policy (transformer models),
pruned to `tuner_num_trials`, each measured for a few real steps.
"""

import dataclasses
import gc
import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


@dataclasses.dataclass
class Trial:
    overrides: Dict[str, Any]
    samples_per_sec: float = float("-inf")
    step_ms: float = float("inf")
    error: Optional[str] = None

    def describe(self) -> str:
        mesh = self.overrides.get("mesh", {}).get("axes", {})
        z = self.overrides.get("zero_optimization", {}).get("stage", "-")
        gas = self.overrides.get("gradient_accumulation_steps", "-")
        remat = self.overrides.get("_remat_policy", "-")
        return (f"mesh={mesh} zero={z} gas={gas} remat={remat}: "
                + (f"{self.samples_per_sec:.1f} samples/s "
                   f"({self.step_ms:.1f} ms/step)"
                   if self.error is None else f"FAILED ({self.error})"))


class Autotuner:
    """In-process grid/random search over engine configurations."""

    def __init__(self, model, base_config: Dict[str, Any], devices=None):
        import jax
        self.model = model
        self.base = dict(base_config)
        self.at_cfg = self.base.get("autotuning", {})
        self.devices = devices
        self.n_devices = len(devices) if devices else jax.device_count()
        self.trials: List[Trial] = []

    # ------------------------------------------------------------------
    def candidates(self) -> List[Dict[str, Any]]:
        n = self.n_devices
        model_cfg = getattr(self.model, "config", None)
        heads = getattr(model_cfg, "num_heads", None)
        layers = getattr(model_cfg, "num_layers", None)
        batch = int(self.base.get("train_batch_size", 8))

        meshes: List[Tuple[Dict[str, int], int]] = []  # (axes, zero stage)
        experts = getattr(model_cfg, "num_experts", 1) or 1
        for tp in _divisors(n):
            if tp > 8 or (heads and heads % tp):
                continue
            rest = n // tp
            # pure-DP variants (stage 0/1/2 equivalent sharding: 0 and 1)
            for stage in (0, 1):
                meshes.append(({"data": rest, "tensor": tp}, stage))
            # fully-sharded variant
            if rest > 1:
                meshes.append(({"fsdp": rest, "tensor": tp}, 3))
            # pipeline variants: stages must divide the layer stack AND the
            # remaining devices (the 1F1B schedule needs gas microbatches,
            # handled by the gas loop below)
            if layers:
                for pp in _divisors(rest):
                    if pp > 1 and pp <= 8 and layers % pp == 0 \
                            and rest // pp >= 1:
                        meshes.append(
                            ({"pipe": pp, "data": rest // pp,
                              "tensor": tp}, 1))
        # expert axis: MoE models shard the expert stack
        if experts > 1:
            for ep in _divisors(min(n, experts)):
                if ep > 1 and experts % ep == 0 and n % ep == 0:
                    meshes.append(({"expert": ep, "data": n // ep}, 1))

        # gas candidates follow the batch's actual divisor structure instead
        # of a hardcoded [1, 2, 4]
        gas_opts = [g for g in _divisors(batch) if g <= 16]
        gas_opts = gas_opts[:max(1, int(
            self.at_cfg.get("num_tuning_micro_batch_sizes", 3)))]

        remat_opts: List[Optional[str]] = [None]
        if model_cfg is not None and hasattr(model_cfg, "remat_policy"):
            remat_opts = [None, "dots_saveable", "save_nothing"]

        out = []
        for (axes, stage), gas, remat in itertools.product(
                meshes, gas_opts, remat_opts):
            dp_like = axes.get("data", 1) * axes.get("fsdp", 1)
            micro = batch // (gas * dp_like) if dp_like else 0
            if micro < 1:
                continue
            ov: Dict[str, Any] = {
                "mesh": {"axes": axes},
                "zero_optimization": {"stage": stage},
                "gradient_accumulation_steps": gas,
            }
            if remat is not None:
                ov["_remat_policy"] = remat
            out.append(ov)
        seed = 0
        if str(self.at_cfg.get("tuner_type", "gridsearch")) == "random":
            rng = np.random.default_rng(seed)
            rng.shuffle(out)
        limit = int(self.at_cfg.get("tuner_num_trials", 50))
        return out[:limit]

    # ------------------------------------------------------------------
    def _build_model(self, overrides):
        remat = overrides.get("_remat_policy")
        cfg = getattr(self.model, "config", None)
        if remat is None or cfg is None:
            return self.model
        from deepspeed_tpu.models import make_model
        return make_model(dataclasses.replace(
            cfg, remat=remat != "none", remat_policy=remat),
            name=self.model.name)

    def _sample_batch(self, batch_size: int):
        cfg = getattr(self.model, "config", None)
        S = min(getattr(cfg, "max_seq_len", 512) or 512, 2048)
        V = getattr(cfg, "vocab_size", 1000)
        r = np.random.default_rng(0)
        return {"input_ids": r.integers(0, V, size=(batch_size, S),
                                        dtype=np.int32)}

    def measure(self, overrides: Dict[str, Any], steps: int = 3) -> Trial:
        import jax
        import deepspeed_tpu
        trial = Trial(overrides=overrides)
        cfg = json.loads(json.dumps(self.base))  # deep copy
        for k, v in overrides.items():
            if k.startswith("_"):
                continue
            if isinstance(v, dict):
                cfg.setdefault(k, {}).update(v)
            else:
                cfg[k] = v
        cfg["autotuning"] = {"enabled": False}
        cfg.setdefault("steps_per_print", 10 ** 9)
        engine = None
        try:
            model = self._build_model(overrides)
            engine, *_ = deepspeed_tpu.initialize(
                model=model, config=cfg, devices=self.devices)
            # the batch must match THIS candidate's resolved global batch, or
            # the samples/sec ranking is fabricated
            batch = self._sample_batch(engine.config.train_batch_size)
            engine.train_batch(batch)          # compile + warmup
            t0 = time.perf_counter()
            for _ in range(steps):
                engine.train_batch(batch)
            jax.block_until_ready(engine.state["step"])
            dt = (time.perf_counter() - t0) / steps
            trial.step_ms = dt * 1e3
            # engine.config solves the batch triad even when the user gave
            # only micro+gas; never index the raw dict for it
            trial.samples_per_sec = engine.config.train_batch_size / dt
        except Exception as e:  # noqa: BLE001 — OOM/compile failures score -inf
            trial.error = f"{type(e).__name__}: {e}"[:200]
        finally:
            del engine
            gc.collect()
        return trial

    # ------------------------------------------------------------------
    def run(self, steps: int = 3) -> Tuple[Dict[str, Any], List[Trial]]:
        cands = self.candidates()
        early_stop = int(self.at_cfg.get("tuner_early_stopping", 5))
        logger.info(f"autotuning: {len(cands)} candidates on "
                    f"{self.n_devices} devices")
        best: Optional[Trial] = None
        since_best = 0
        for ov in cands:
            t = self.measure(ov, steps=steps)
            self.trials.append(t)
            logger.info("autotuning trial: " + t.describe())
            if best is None or t.samples_per_sec > best.samples_per_sec:
                best, since_best = t, 0
            else:
                since_best += 1
                if early_stop and since_best >= early_stop:
                    logger.info("autotuning: early stop "
                                f"({early_stop} trials without improvement)")
                    break
        results_dir = self.at_cfg.get("results_dir", "autotuning_results")
        try:
            os.makedirs(results_dir, exist_ok=True)
            with open(os.path.join(results_dir, "results.json"), "w") as f:
                json.dump([dataclasses.asdict(t) for t in self.trials], f,
                          indent=2, default=str)
        except OSError as e:
            logger.warning(f"autotuning: could not write results: {e}")
        if best is None or best.error is not None:
            raise RuntimeError("autotuning: every candidate failed; last "
                               f"error: {self.trials[-1].error}")
        logger.info("autotuning BEST: " + best.describe())
        return best.overrides, self.trials


def autotune_config(model, config: Dict[str, Any], devices=None,
                    steps: int = 3):
    """Run the search; returns (merged_config, model) — the base config with
    the winning overrides merged in (autotuning disabled so the resulting
    engine builds directly) and the model, rebuilt if the winning trial chose
    a different remat policy."""
    tuner = Autotuner(model, config, devices=devices)
    best, _ = tuner.run(steps=steps)
    merged = json.loads(json.dumps(config))
    for k, v in best.items():
        if k.startswith("_"):
            continue
        if isinstance(v, dict):
            merged.setdefault(k, {}).update(v)
        else:
            merged[k] = v
    merged["autotuning"] = {"enabled": False}
    return merged, tuner._build_model(best)
