from deepspeed_tpu.autotuning.autotuner import Autotuner, autotune_config
