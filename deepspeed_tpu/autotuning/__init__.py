from deepspeed_tpu.autotuning.autotuner import Autotuner, autotune_config
from deepspeed_tpu.autotuning.scheduler import (Experiment, ResourceManager,
                                                schedule_experiments)
