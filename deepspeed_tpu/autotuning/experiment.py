"""One autotuning experiment process: measure a config, write result JSON.

Reference: the per-experiment subprocess the reference's scheduler launches
(``deepspeed/autotuning/scheduler.py`` run_experiment -> ds train script);
here the measurement IS the engine — build, warm up, time a few steps,
emit ``{"samples_per_sec", "step_ms"}`` (or ``{"error"}``) to the result
path the scheduler polls.

Usage: ``python -m deepspeed_tpu.autotuning.experiment cfg.json out.json``.
The config may carry an ``_experiment`` section: ``{"steps": N,
"model": {TransformerConfig kwargs}}`` — without a model section a tiny
default transformer is measured (mesh/zero/gas relative rankings transfer).
"""

import json
import sys
import time


def run_experiment(cfg_path: str, out_path: str) -> int:
    with open(cfg_path) as f:
        config = json.load(f)
    exp = config.pop("_experiment", {}) or {}
    steps = int(exp.get("steps", 3))
    out = {}
    try:
        import numpy as np
        import jax
        import deepspeed_tpu
        from deepspeed_tpu.models import TransformerConfig, make_model
        mk = dict(exp.get("model") or {})
        mk.setdefault("vocab_size", 256)
        mk.setdefault("hidden_size", 64)
        mk.setdefault("num_layers", 2)
        mk.setdefault("num_heads", 4)
        mk.setdefault("max_seq_len", 128)
        model = make_model(TransformerConfig(**mk), name="autotune-exp")
        config.setdefault("steps_per_print", 10 ** 9)
        config["autotuning"] = {"enabled": False}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
        B = engine.config.train_batch_size
        S = mk["max_seq_len"]
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, mk["vocab_size"], (B, S),
                                           dtype=np.int32)}
        engine.train_batch(batch)            # compile + warmup
        t0 = time.perf_counter()
        for _ in range(steps):
            engine.train_batch(batch)
        if engine.state is not None:
            jax.block_until_ready(engine.state["step"])
        dt = (time.perf_counter() - t0) / steps
        out = {"samples_per_sec": B / dt, "step_ms": dt * 1e3}
    except Exception as e:  # noqa: BLE001 — the scheduler ranks failures -inf
        out = {"error": f"{type(e).__name__}: {e}"[:300]}
    with open(out_path, "w") as f:
        json.dump(out, f)
    return 0 if "error" not in out else 1


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(run_experiment(sys.argv[1], sys.argv[2]))
