"""Multi-host experiment scheduler for the autotuner.

Reference: ``deepspeed/autotuning/scheduler.py`` (ResourceManager — an
experiment queue with per-node slot accounting: each experiment is launched
as subprocesses on a reserved node subset via the multinode runner, results
are parsed from the experiment directory, nodes are released on completion).

TPU-native re-design: an experiment is a JSON engine config measured by
``python -m deepspeed_tpu.autotuning.experiment <cfg.json> <out.json>`` —
one process per host (a TPU host's chips share one jax client, so hostfile
slots document chip counts, they don't multiply processes). The manager
partitions the host pool greedily: candidates whose mesh fits a SUBSET of
hosts run concurrently on disjoint subsets (the reference's node
reservation), full-pool candidates run alone. Launching rides the
``launcher.multinode_runner`` backends; single-host pools degrade to a
plain local subprocess, which is also how the unit tests execute a real
experiment end-to-end.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass
class Experiment:
    exp_id: int
    config: Dict[str, Any]
    num_hosts: int = 1                       # hosts this candidate needs
    hosts: List[str] = dataclasses.field(default_factory=list)
    status: str = "pending"                  # pending|running|done|failed
    result: Optional[Dict[str, Any]] = None
    error: Optional[str] = None

    @property
    def metric(self) -> float:
        if self.result and "samples_per_sec" in self.result:
            return float(self.result["samples_per_sec"])
        return float("-inf")


def hosts_needed(config: Dict[str, Any], chips_per_host: int) -> int:
    """Host count a candidate's mesh needs: ceil(world / chips_per_host)."""
    axes = (config.get("mesh") or {}).get("axes") or {}
    world = 1
    for v in axes.values():
        world *= int(v)
    return max(1, -(-world // max(1, chips_per_host)))


class ResourceManager:
    """Greedy host-pool partitioner + experiment launcher/collector.

    ``launch`` is injectable (tests; custom transports). The default
    launches the experiment module locally when the group is this host,
    or via the pdsh multinode runner otherwise, writing the result JSON
    into ``results_dir/exp_<id>/result.json`` exactly like the reference's
    per-experiment directories.

    **Shared-filesystem requirement**: remotely-launched experiments write
    ``result.json`` under ``results_dir`` *on the remote host*, and
    ``_collect`` reads that same path *on this host* — so for multi-host
    pools ``results_dir`` must live on storage every host mounts (NFS /
    gcsfuse; TPU pods already mount one for checkpoints). With a local-only
    results_dir every remote experiment reports "no result file". Pass a
    custom ``launch`` that fetches results over its own transport to lift
    the requirement.
    """

    def __init__(self, hosts: List[str], chips_per_host: int = 4,
                 results_dir: str = "autotuning_exps",
                 launch: Optional[Callable[[Experiment], None]] = None,
                 poll_s: float = 1.0, timeout_s: float = 3600.0):
        self.hosts = list(hosts) or ["localhost"]
        self.chips_per_host = chips_per_host
        self.results_dir = results_dir
        self._launch = launch or self._launch_default
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        self._procs: Dict[int, Any] = {}

    # ------------------------------------------------------------------
    def _exp_dir(self, exp: Experiment) -> str:
        d = os.path.join(self.results_dir, f"exp_{exp.exp_id}")
        os.makedirs(d, exist_ok=True)
        return d

    @staticmethod
    def _is_local(hosts: List[str]) -> bool:
        return set(hosts) <= {"localhost", "127.0.0.1", os.uname().nodename}

    def _launch_default(self, exp: Experiment):
        d = self._exp_dir(exp)
        cfg_path = os.path.join(d, "config.json")
        out_path = os.path.join(d, "result.json")
        with open(cfg_path, "w") as f:
            json.dump(exp.config, f)
        script = [sys.executable, "-m", "deepspeed_tpu.autotuning.experiment",
                  cfg_path, out_path]
        local = self._is_local(exp.hosts)
        if local:
            self._procs[exp.exp_id] = subprocess.Popen(
                script, stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT)
        else:
            from deepspeed_tpu.launcher.multinode_runner import PDSHRunner
            runner = PDSHRunner({h: self.chips_per_host for h in exp.hosts},
                                script, env=dict(os.environ))
            self._procs[exp.exp_id] = subprocess.Popen(
                runner.get_cmd(), stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT)

    def _collect(self, exp: Experiment):
        out_path = os.path.join(self._exp_dir(exp), "result.json")
        proc = self._procs.pop(exp.exp_id, None)
        rc = proc.wait() if proc is not None else 0
        if os.path.exists(out_path):
            with open(out_path) as f:
                exp.result = json.load(f)
            exp.status = "failed" if exp.result.get("error") else "done"
            exp.error = exp.result.get("error")
        else:
            exp.status = "failed"
            if exp.hosts and not self._is_local(exp.hosts):
                # the most common cause is NOT the experiment failing but
                # results_dir living on host-local storage (see class doc)
                exp.error = (
                    f"no result file at {out_path} (rc={rc}) — experiment "
                    f"ran remotely on {exp.hosts}; results_dir "
                    f"'{self.results_dir}' must be on a filesystem shared "
                    "by every host (NFS/gcsfuse), or pass a custom launch "
                    "that fetches results back")
                logger.error(f"autotuning exp {exp.exp_id}: {exp.error}")
            else:
                exp.error = f"no result file (rc={rc})"

    def _done(self, exp: Experiment) -> bool:
        proc = self._procs.get(exp.exp_id)
        return proc is None or proc.poll() is not None

    # ------------------------------------------------------------------
    def schedule(self, configs: List[Dict[str, Any]]) -> List[Experiment]:
        """Run every candidate; disjoint host groups run CONCURRENTLY.
        Returns the experiments sorted most-throughput-first."""
        exps = [Experiment(exp_id=i, config=c,
                           num_hosts=min(len(self.hosts),
                                         hosts_needed(c, self.chips_per_host)))
                for i, c in enumerate(configs)]
        pending = list(exps)
        running: List[Experiment] = []
        free = list(self.hosts)
        t0 = time.time()
        while pending or running:
            # reap finished
            for exp in list(running):
                if self._done(exp):
                    self._collect(exp)
                    running.remove(exp)
                    free.extend(exp.hosts)
                    logger.info(
                        f"autotuning exp {exp.exp_id}: {exp.status}"
                        + (f" {exp.metric:.1f} samples/s"
                           if exp.status == "done" else f" ({exp.error})"))
            # greedy assignment onto free hosts
            for exp in list(pending):
                if exp.num_hosts <= len(free):
                    exp.hosts = [free.pop(0) for _ in range(exp.num_hosts)]
                    exp.status = "running"
                    pending.remove(exp)
                    running.append(exp)
                    self._launch(exp)
            if time.time() - t0 > self.timeout_s:
                for exp in running:
                    proc = self._procs.pop(exp.exp_id, None)
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                    exp.status = "failed"
                    exp.error = "timeout"
                break
            if running:
                time.sleep(self.poll_s)
        return sorted(exps, key=lambda e: e.metric, reverse=True)


def schedule_experiments(configs: List[Dict[str, Any]],
                         hosts: Optional[List[str]] = None,
                         chips_per_host: int = 4,
                         results_dir: str = "autotuning_exps",
                         **kw) -> List[Experiment]:
    """Convenience entry: partition `hosts` and measure every candidate."""
    rm = ResourceManager(hosts or ["localhost"],
                         chips_per_host=chips_per_host,
                         results_dir=results_dir, **kw)
    return rm.schedule(configs)
