"""Step tracing: host-side span recorder + windowed jax.profiler capture.

Reference analogue: ``deepspeed/utils/timer.py`` wall-clock timers plus the
``flops_profiler``'s latency printouts — all eager, all per step. Under async
dispatch a per-step host timestamp measures DISPATCH, not execution
(utils/timer.py docs), so the tracer records exactly the phases the HOST owns
in ``engine.train_batches``:

  * ``dispatch``  — queueing the jitted step (Python + jax dispatch overhead)
  * ``prefetch``  — the sharding-aware device_put of the next batch
                    (PrefetchLoader top-up)
  * ``data_wait`` — blocking on the wrapped iterator for the next batch
  * ``block``     — backpressure: waiting on the oldest in-flight step's
                    output once the dispatch window is full (the honest
                    "device is the bottleneck" signal)

Spans are appended to a bounded ring and exported as Chrome-trace JSON
(``chrome://tracing`` / Perfetto "traceEvents" format). Device-side timing
comes from the complementary windowed ``jax.profiler.start_trace`` capture
(:meth:`StepTracer.maybe_profile`), configured via ``telemetry.trace``.

Per-span cost is two ``perf_counter`` calls and a deque append — safe to
leave on in the steady-state loop.
"""

import collections
import contextlib
import json
import os
import time
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger


class StepTracer:
    def __init__(self, trace_cfg=None, max_events: int = 20000):
        self.events: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=max(16, int(max_events)))
        self._window_s: Dict[str, float] = {}
        self._window_n: Dict[str, int] = {}
        self._t0 = time.perf_counter()
        self._trace_cfg = trace_cfg
        self._pid = os.getpid()
        self._profiling = False
        self._profile_done = False
        self._first_step = None   # first step this run observed
        self._stop_at = None      # dynamic stop step of an open capture

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "step"):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self.events.append({
                "name": name, "cat": cat, "ph": "X",
                "ts": (t0 - self._t0) * 1e6, "dur": (t1 - t0) * 1e6,
                "pid": self._pid, "tid": 0,
            })
            self._window_s[name] = self._window_s.get(name, 0.0) + (t1 - t0)
            self._window_n[name] = self._window_n.get(name, 0) + 1

    def instant(self, name: str, args: Optional[Dict[str, Any]] = None):
        """Point event (anomalies, phase switches) in the same timeline."""
        ev = {"name": name, "cat": "event", "ph": "i", "s": "g",
              "ts": (time.perf_counter() - self._t0) * 1e6,
              "pid": self._pid, "tid": 0}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def drain_window(self) -> Dict[str, float]:
        """Per-window phase totals (``<phase>_ms`` / ``<phase>_count``),
        resetting the window. Pure host work — called from the engine's
        boundary drain."""
        out: Dict[str, float] = {}
        for name, sec in self._window_s.items():
            out[f"{name}_ms"] = sec * 1000.0
            out[f"{name}_count"] = self._window_n.get(name, 0)
        self._window_s.clear()
        self._window_n.clear()
        return out

    def export_chrome_trace(self, path: str) -> str:
        """Write the span ring as Chrome-trace JSON ({"traceEvents": [...]})
        loadable by chrome://tracing and Perfetto."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": list(self.events),
                       "displayTimeUnit": "ms"}, f)
        return path

    # -- windowed device-side profiler capture ---------------------------
    def maybe_profile(self, step: int) -> None:
        """Drive the configured ``jax.profiler`` capture window: start
        inside [start_step, start_step+num_steps), stop once past the end.
        One window per run; failures disable the capture rather than the
        training. The start is bounded above so a job resumed from a
        checkpoint PAST the window doesn't begin a mis-placed capture; an
        ``atexit`` hook finalizes a capture still open when the process
        exits before the stop step (the profile files are written at stop)."""
        cfg = self._trace_cfg
        if cfg is None or not getattr(cfg, "enabled", False):
            return
        end = cfg.start_step + cfg.num_steps
        if self._first_step is None:
            self._first_step = step
        if not self._profiling and not self._profile_done:
            if step >= end and self._first_step >= end:
                # the RUN began past the window (checkpoint resume): a
                # capture here would be mis-placed. A fused K-step stride
                # that jumps over the window mid-run is different — the
                # branch below starts a shifted capture instead of losing it
                self._profile_done = True
                return
            if step >= cfg.start_step:
                try:
                    import atexit
                    import jax
                    os.makedirs(cfg.output_dir, exist_ok=True)
                    jax.profiler.start_trace(cfg.output_dir)
                    self._profiling = True
                    self._stop_at = step + cfg.num_steps
                    atexit.register(self.stop_profile)  # idempotent
                    logger.info(f"telemetry: jax.profiler trace started at "
                                f"step {step} -> {cfg.output_dir}")
                except Exception as e:  # noqa: BLE001 - best-effort
                    logger.warning(f"telemetry: profiler trace failed to "
                                   f"start ({e!r}); disabling capture")
                    self._profile_done = True
        elif self._profiling and step >= (self._stop_at or end):
            self.stop_profile()

    def stop_profile(self) -> None:
        if not self._profiling:
            return
        try:
            import jax
            jax.profiler.stop_trace()
            logger.info("telemetry: jax.profiler trace stopped")
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: profiler trace failed to stop ({e!r})")
        finally:
            self._profiling = False
            self._profile_done = True

    def close(self) -> None:
        self.stop_profile()
