"""Unified telemetry: in-graph accumulators, step tracing, anomaly detection.

The observability layer for the async hot loop (ROADMAP: production-scale
serving with zero added steady-state syncs). Four pieces:

  accumulators — cumulative device counters in the donated ``state
                 ["telemetry"]`` leaf, advanced inside the jitted step and
                 drained through ``engine._log_step``'s ONE batched
                 device_get; windows are host-side snapshot diffs
  tracing      — host span recorder around the dispatch/prefetch/block
                 phases of ``engine.train_batches`` (Chrome-trace export)
                 plus windowed ``jax.profiler`` capture
  anomaly      — structured-severity events (loss spikes, grad-norm drift,
                 overflow bursts, dispatch-stall regressions) from the
                 drained window stats
  join         — graft-lint's static collective census and XLA's compiled
                 flops priced by the observed step rate: modeled comms
                 bytes/sec and per-window MFU as monitor events

The serving fleet (PR 18) adds two request-tier pieces on the same rules:

  request_trace — per-request host-clock spans across the whole lifecycle
                  (admission → prefill chunks → decode quanta → drain/
                  migration), stitched across replicas through drain-state
                  v3 and merged into one Chrome trace (replica = process)
  exposition    — mergeable fixed-edge histograms + Prometheus text
                  format for the router's ``fleet_stats()`` rollup

The robustness subsystem (``deepspeed_tpu/robustness``) publishes its
recovery decisions on the same record stream: ``ckpt_fallback``,
``fault_recovered``, ``ckpt_save_failed``, ``preempted`` and
``fault_injected`` records are drained from ``robustness.events`` by
``engine._log_step`` at the SAME window boundary (and into the same JSONL
sink) as the telemetry records — fault handling is observable with zero
added steady-state syncs.

Enable with config ``{"telemetry": {"enabled": true}}``; see the README
"Observability" and "Fault tolerance" sections for the full reference.
"""

from deepspeed_tpu.telemetry.accumulators import (HIST_BUCKETS, HIST_LOG2_MIN,
                                                  HostWindow, accumulate,
                                                  init_leaf,
                                                  update_to_param_ratio,
                                                  window_stats)
from deepspeed_tpu.telemetry.anomaly import (SEVERITY_NUM, AnomalyDetector,
                                             severity_num)
from deepspeed_tpu.telemetry.exposition import (Histogram, parse_exposition,
                                                render_prometheus)
from deepspeed_tpu.telemetry.join import joined_rates, static_step_cost
from deepspeed_tpu.telemetry.request_trace import (RequestTracer,
                                                   merge_chrome_trace)
from deepspeed_tpu.telemetry.tracing import StepTracer

__all__ = [
    "HIST_BUCKETS", "HIST_LOG2_MIN", "AnomalyDetector", "Histogram",
    "HostWindow", "RequestTracer", "SEVERITY_NUM", "StepTracer", "accumulate",
    "init_leaf", "joined_rates", "merge_chrome_trace", "parse_exposition",
    "render_prometheus", "severity_num", "static_step_cost",
    "update_to_param_ratio", "window_stats",
]
