"""Fleet metric rollup primitives: mergeable histograms + text exposition.

The router sees the pod — per-replica heartbeat ``meta`` payloads plus the
frozen last-seen meta of drained/dead replicas — but PR 16's ``stats()``
only summed counters. This module adds the two pieces a scrape needs:

* :class:`Histogram` — fixed-bucket-edge histogram whose *merge* is exact
  (same edges ⇒ bucket-wise add). Replicas serialize compact
  ``to_dict`` payloads in their heartbeats; the router merges them without
  ever seeing the raw samples. Edges default to a latency-friendly
  geometric ladder but are part of the serialized payload, so a version
  skew between replica and router degrades to "ignore, don't corrupt".
* :func:`render_prometheus` — Prometheus text exposition (line format):
  gauges/counters as single samples, histograms as cumulative
  ``_bucket{le="..."}`` series plus ``_sum``/``_count``. A plain HTTP
  handler returning this string is a scrape endpoint; the repo stays
  stdlib-only.
* :func:`parse_exposition` — inverse of the renderer, for round-trip
  pinning in ``test_fleet_obs`` (and for anyone gluing two routers).
"""

import math
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = ["Histogram", "render_prometheus", "parse_exposition",
           "DEFAULT_EDGES_MS", "DEPTH_EDGES", "FRACTION_EDGES"]

# geometric ladder 1ms..~16s: wide enough for TTFT on a cold replica,
# fine enough near the bottom for CPU-test ITL
DEFAULT_EDGES_MS: Tuple[float, ...] = tuple(
    float(v) for v in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                       1024, 2048, 4096, 8192, 16384))
# queue depth / running-count style small integers
DEPTH_EDGES: Tuple[float, ...] = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0,
                                  64.0, 128.0)
# occupancy fractions (pool / adapter slots), 0..1
FRACTION_EDGES: Tuple[float, ...] = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


class Histogram:
    """Fixed-edge histogram with exact merge. ``counts[i]`` is the number
    of samples ``<= edges[i]``-exclusive-of-lower-buckets (i.e. classic
    per-bucket counts, NOT cumulative); an implicit overflow bucket holds
    samples above the last edge. Rendering converts to Prometheus's
    cumulative ``le`` convention."""

    def __init__(self, edges: Iterable[float] = DEFAULT_EDGES_MS):
        self.edges: Tuple[float, ...] = tuple(float(e) for e in edges)
        if list(self.edges) != sorted(self.edges):
            raise ValueError("histogram edges must be ascending")
        self.counts: List[int] = [0] * (len(self.edges) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        i = 0
        for i, e in enumerate(self.edges):  # noqa: B007 - tiny fixed ladder
            if v <= e:
                break
        else:
            i = len(self.edges)
        self.counts[i] += 1
        self.sum += v
        self.count += 1

    def observe_many(self, values: Iterable[float]) -> None:
        for v in values:
            self.observe(v)

    def merge(self, other: "Histogram") -> "Histogram":
        if other.edges != self.edges:
            raise ValueError(f"edge mismatch: {other.edges} vs {self.edges}")
        for i, c in enumerate(other.counts):
            self.counts[i] += int(c)
        self.sum += other.sum
        self.count += other.count
        return self

    # -- wire format (heartbeat meta / drain stats) ----------------------
    def to_dict(self) -> Dict[str, Any]:
        return {"edges": list(self.edges), "counts": list(self.counts),
                "sum": self.sum, "count": self.count}

    @classmethod
    def from_dict(cls, d: Optional[Mapping[str, Any]]) -> Optional["Histogram"]:
        """Rehydrate a wire payload; malformed/foreign payloads return
        ``None`` (version-skew rule: ignore, don't corrupt)."""
        if not isinstance(d, Mapping):
            return None
        try:
            h = cls(d["edges"])
            counts = [int(c) for c in d["counts"]]
            if len(counts) != len(h.counts):
                return None
            h.counts = counts
            h.sum = float(d.get("sum", 0.0))
            h.count = int(d.get("count", sum(counts)))
            return h
        except (KeyError, TypeError, ValueError):
            return None

    def quantile(self, q: float) -> float:
        """Edge-resolution quantile (upper edge of the bucket holding the
        q-th sample; +inf bucket reports the last edge)."""
        if self.count <= 0:
            return 0.0
        target = max(1, math.ceil(min(max(q, 0.0), 1.0) * self.count))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.edges[i] if i < len(self.edges) else self.edges[-1]
        return self.edges[-1]


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(metrics: Mapping[str, Any],
                      prefix: str = "dstpu") -> str:
    """Render a ``{name: value}`` mapping as Prometheus text exposition.

    Values may be numbers (rendered as gauges), :class:`Histogram`
    instances, or dicts that rehydrate via :meth:`Histogram.from_dict`.
    Non-numeric, non-histogram values are skipped — the caller can pass a
    whole ``fleet_stats()`` snapshot without pre-filtering."""
    lines: List[str] = []
    for name in sorted(metrics):
        val = metrics[name]
        full = f"{prefix}_{name}" if prefix else name
        if isinstance(val, Mapping):
            val = Histogram.from_dict(val)
            if val is None:
                continue
        if isinstance(val, Histogram):
            lines.append(f"# TYPE {full} histogram")
            cum = 0
            for i, e in enumerate(val.edges):
                cum += val.counts[i]
                lines.append(f'{full}_bucket{{le="{_fmt(e)}"}} {cum}')
            lines.append(f'{full}_bucket{{le="+Inf"}} {val.count}')
            lines.append(f"{full}_sum {_fmt(val.sum)}")
            lines.append(f"{full}_count {val.count}")
        elif isinstance(val, bool):
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {int(val)}")
        elif isinstance(val, (int, float)):
            if isinstance(val, float) and math.isnan(val):
                continue
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {_fmt(val)}")
    return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> Dict[str, Any]:
    """Inverse of :func:`render_prometheus`: gauges come back as floats,
    histograms as :class:`Histogram` (per-bucket counts reconstructed from
    the cumulative series)."""
    gauges: Dict[str, float] = {}
    buckets: Dict[str, List[Tuple[float, int]]] = {}
    sums: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        if "_bucket{le=" in name:
            base, _, le = name.partition("_bucket{le=")
            le = le.rstrip("}").strip('"')
            edge = math.inf if le == "+Inf" else float(le)
            buckets.setdefault(base, []).append((edge, int(float(val))))
        elif name.endswith("_sum") and name[:-4] in buckets:
            sums[name[:-4]] = float(val)
        elif name.endswith("_count") and name[:-6] in buckets:
            counts[name[:-6]] = int(float(val))
        else:
            gauges[name] = float(val)
    out: Dict[str, Any] = dict(gauges)
    for base, series in buckets.items():
        series.sort(key=lambda p: p[0])
        edges = [e for e, _ in series if e != math.inf]
        h = Histogram(edges)
        prev = 0
        for i, (_, cum) in enumerate(series):
            h.counts[i] = cum - prev
            prev = cum
        h.count = counts.get(base, prev)
        h.sum = sums.get(base, 0.0)
        out[base] = h
    return out
