"""Per-request distributed tracing for the serving fleet.

The training loop got a host-side span recorder in PR 3
(:class:`~deepspeed_tpu.telemetry.tracing.StepTracer`); the serving tier —
continuous-batching rounds, chunked prefill, preemption, adapter paging,
drain/migration (PRs 9–16) — had only flat counters. This module records a
REQUEST-centric timeline instead of a step-centric one: every request
carries a trace id from admission to finish, accumulating host-wall-clock
spans for each lifecycle phase it passes through (admission, queue wait,
each prefill chunk, each decode quantum it participates in, preemption and
re-prefill, adapter page-in, drain and migration).

Design rules, in priority order:

* **Zero added device syncs.** Span bookkeeping is two ``perf_counter``
  calls and a deque append — no ``device_get``, no ``block_until_ready``.
  A tracing-armed engine is bit-identical to an untraced one (pinned by
  ``test_fleet_obs``). The ``on_span`` hook is the documented defect seam:
  anything it does per span is on the caller, and :data:`device_syncs`
  counts self-reported syncs so the ``tracing-sync-leak`` corpus twin and
  the doctor's overhead gate can name the offender.
* **Stitching across replicas.** Timestamps are anchored to the UNIX epoch
  (``time.time() - perf_counter()`` captured once at construction), so
  per-replica streams share one time axis. :meth:`RequestTracer.context`
  serializes a request's trace (id + spans) into the drain-state v3 record;
  :meth:`RequestTracer.adopt` on the destination replica re-appends those
  spans under the SAME trace id with their ORIGINAL replica tag — the
  merged Chrome trace shows one continuous trace spanning both process
  rows.
* **Bounded.** Events live in a ring (default 65536); a hot fleet cannot
  grow host memory without bound. Finished requests' id bookkeeping is
  dropped on :meth:`end`.

Export is Chrome-trace JSON ("traceEvents"): one *process* row per replica
(``merge_chrome_trace`` assigns pids and emits ``process_name`` metadata),
one *thread* row per request within its replica, ``args.trace`` carrying
the trace id so Perfetto's flow queries can follow a migration.
"""

import collections
import contextlib
import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

__all__ = ["RequestTracer", "merge_chrome_trace"]


class RequestTracer:
    """Host-clock span recorder keyed by request id.

    ``replica`` tags every span (and becomes the process row at export);
    ``on_span`` is an optional per-span callback (the defect seam the
    ``tracing-sync-leak`` corpus exercises — keep it host-only or pay the
    overhead gate). If the hook performs a device sync it must account for
    it by incrementing :data:`device_syncs`; the built-in paths never do.
    """

    def __init__(self, replica: str = "r0", max_events: int = 65536,
                 on_span: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.replica = str(replica)
        self.events: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=max(64, int(max_events)))
        self.on_span = on_span
        self.device_syncs = 0        # self-reported by leaky on_span hooks
        self._seq = 0                # per-tracer trace-id sequence
        self._ids: Dict[str, str] = {}       # rid -> trace id
        # one wall-clock anchor per tracer: perf_counter deltas become
        # unix-epoch microseconds, so independently-started replicas merge
        # on a single time axis without any cross-host coordination
        self._anchor = time.time() - time.perf_counter()

    # -- lifecycle -------------------------------------------------------
    def begin(self, rid: str, trace_id: Optional[str] = None) -> str:
        """Open (or re-open, for resubmission) a request's trace. Returns
        the trace id — deterministic ``<replica>/<rid>.<seq>`` unless an
        inherited id is supplied (migration adoption goes through
        :meth:`adopt` instead)."""
        if trace_id is None:
            trace_id = self._ids.get(rid)
        if trace_id is None:
            trace_id = f"{self.replica}/{rid}.{self._seq}"
            self._seq += 1
        self._ids[rid] = trace_id
        return trace_id

    def trace_id(self, rid: str) -> Optional[str]:
        return self._ids.get(rid)

    def end(self, rid: str) -> None:
        """Drop id bookkeeping for a finished/cancelled request. Its spans
        stay in the ring until evicted."""
        self._ids.pop(rid, None)

    # -- recording -------------------------------------------------------
    def _now(self) -> float:
        return self._anchor + time.perf_counter()

    def epoch(self, perf_t: float) -> float:
        """Convert a ``time.perf_counter()`` stamp (the scheduler's
        ``submit_t`` basis) to this tracer's unix-epoch seconds."""
        return self._anchor + perf_t

    def _append(self, ev: Dict[str, Any]) -> None:
        self.events.append(ev)
        if self.on_span is not None:
            self.on_span(ev)

    def add_span(self, rid: str, name: str, t0: float, t1: float,
                 cat: str = "serve", **args: Any) -> None:
        """Record a completed span from explicit HOST wall-clock seconds
        (unix epoch — pass ``submit_t``-style stamps directly). Used for
        phases whose start predates the tracer call site (queue wait)."""
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": t0 * 1e6, "dur": max(0.0, (t1 - t0)) * 1e6,
              "replica": self.replica, "trace": self._ids.get(rid, rid),
              "rid": rid}
        if args:
            ev["args"] = args
        self._append(ev)

    @contextlib.contextmanager
    def span(self, rid: str, name: str, cat: str = "serve", **args: Any):
        t0 = self._now()
        try:
            yield
        finally:
            self.add_span(rid, name, t0, self._now(), cat=cat, **args)

    def instant(self, rid: str, name: str, **args: Any) -> None:
        ev = {"name": name, "cat": "event", "ph": "i", "s": "t",
              "ts": self._now() * 1e6,
              "replica": self.replica, "trace": self._ids.get(rid, rid),
              "rid": rid}
        if args:
            ev["args"] = args
        self._append(ev)

    # -- migration stitching ---------------------------------------------
    def context(self, rid: str) -> Dict[str, Any]:
        """Serializable trace context for a drain-state v3 record: the
        trace id plus every span recorded for the request SO FAR (original
        replica tags kept — the destination must not rewrite history)."""
        tid = self._ids.get(rid, f"{self.replica}/{rid}.?")
        return {"id": tid,
                "spans": [dict(e) for e in self.events
                          if e.get("rid") == rid]}

    def adopt(self, rid: str, ctx: Optional[Dict[str, Any]]) -> str:
        """Resume a migrated request's trace on THIS replica: inherit the
        trace id and re-append the source replica's spans verbatim so a
        single export from the destination still shows the whole life."""
        if not ctx:
            return self.begin(rid)
        tid = str(ctx.get("id") or f"{self.replica}/{rid}.{self._seq}")
        self._ids[rid] = tid
        for ev in ctx.get("spans") or []:
            e = dict(ev)
            e.setdefault("replica", "?")
            e["trace"] = tid
            e["rid"] = rid
            self.events.append(e)   # no on_span: history, not new activity
        return tid

    # -- export ----------------------------------------------------------
    def export(self) -> Dict[str, Any]:
        """One replica's stream, mergeable by :func:`merge_chrome_trace`."""
        return {"replica": self.replica, "events": list(self.events)}


def merge_chrome_trace(streams: Iterable[Dict[str, Any]],
                       path: Optional[str] = None) -> Dict[str, Any]:
    """Merge per-replica streams (``RequestTracer.export`` dicts) into one
    Chrome-trace JSON. Each distinct replica tag — including tags carried
    by ADOPTED spans from a replica that no longer exists — gets its own
    process row; requests are thread rows within a replica. A migrated
    request appears in two process rows under one ``args.trace`` id."""
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    out: List[Dict[str, Any]] = []

    def pid_of(rep: str) -> int:
        if rep not in pids:
            pids[rep] = len(pids) + 1
            out.append({"name": "process_name", "ph": "M", "pid": pids[rep],
                        "tid": 0, "args": {"name": f"replica {rep}"}})
        return pids[rep]

    for stream in streams:
        default_rep = str(stream.get("replica", "?"))
        for ev in stream.get("events", []):
            rep = str(ev.get("replica", default_rep))
            pid = pid_of(rep)
            key = (rep, ev.get("rid", ""))
            if key not in tids:
                tids[key] = len(tids) + 1
            e = {k: v for k, v in ev.items()
                 if k not in ("replica", "trace", "rid")}
            e["pid"] = pid
            e["tid"] = tids[key]
            args = dict(e.get("args") or {})
            args["trace"] = ev.get("trace", "")
            args["rid"] = ev.get("rid", "")
            e["args"] = args
            out.append(e)
    trace = {"traceEvents": out, "displayTimeUnit": "ms"}
    if path:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
        try:
            from deepspeed_tpu.robustness import events as rb_events
            rb_events.emit("trace_export", path=path, events=len(out),
                           replicas=len(pids))
        except Exception:  # noqa: BLE001 - export must not fail on emit
            pass
    return trace
