"""Static x runtime join: compiled-program costs priced by the observed rate.

graft-lint (``deepspeed_tpu/analysis``) already reads the compiled step
program's collective census statically; XLA's ``cost_analysis`` knows the
program's post-fusion FLOPs. Neither says anything about TIME — and the
runtime telemetry knows the observed step rate but nothing about what a step
*is*. Multiplying the two yields first-class monitor events no single layer
could produce:

  * ``modeled_comm_bytes_per_sec`` — census bytes/step x steps/sec: the wire
    load this config puts on ICI/DCN at the observed rate (the reference can
    only estimate this by watching NCCL with the comms logger)
  * ``window_mfu`` — compiled flops/step x steps/sec / chip peak: achieved
    MFU per steps_per_print window, continuously, not just when the flops
    profiler runs its one-shot report

The static half is computed ONCE (lazily, at the first window boundary) from
the same jitted callable the engine dispatches, lowered on the abstract args
captured at dispatch time — off the steady-state path, no execution, no
extra fetch.
"""

from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger


def static_step_cost(jitted, abstract_args, *, mesh=None,
                     divisor: int = 1) -> Optional[Dict[str, Any]]:
    """Lower+compile ``jitted`` on ``abstract_args`` and read XLA's cost
    analysis plus the collective census. ``divisor`` normalizes a fused
    K-step program back to per-step costs. Returns None when the backend
    can't answer (no cost model, lowering failure)."""
    import contextlib
    try:
        ctx = mesh if mesh is not None else contextlib.nullcontext()
        with ctx:
            compiled = jitted.lower(*abstract_args).compile()
        flops = 0
        bytes_accessed = 0
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            if ca:
                flops = int(ca.get("flops", 0))
                bytes_accessed = int(ca.get("bytes accessed", 0))
        except Exception:  # noqa: BLE001 - cost model is backend-dependent
            pass
        from deepspeed_tpu.analysis.hlo_parse import (collective_census,
                                                      estimate_peak_hbm,
                                                      overlap_summary,
                                                      parse_overlap)
        # ONE text dump feeds everything: the collective census
        # (kind/bytes), the scheduled-HLO overlap classification (how much
        # of that wire load is hidden under compute vs exposed step
        # latency), and the static peak-HBM liveness model
        text = compiled.as_text()
        overlap_ops = parse_overlap(text)
        census = collective_census(overlap_ops)
        comm_bytes = sum(c["bytes"] for c in census.values())
        overlap = overlap_summary(overlap_ops)
        # NOT divided by k: a correctly-fused K-step program carries its
        # inter-step state at boundary shardings, so its peak stays ~1x
        # the single step's — dividing would claim K-fused uses 1/K the
        # memory of one step, which is exactly backwards
        peak_hbm = estimate_peak_hbm(text).peak_bytes
        k = max(1, int(divisor))
        return {
            "modeled_peak_hbm": peak_hbm,
            "flops_per_step": flops // k,
            "bytes_accessed_per_step": bytes_accessed // k,
            "comm_bytes_per_step": comm_bytes // k,
            "exposed_comm_bytes_per_step": overlap["exposed"]["bytes"] // k,
            "overlapped_comm_bytes_per_step":
                overlap["overlapped"]["bytes"] // k,
            "census": {kind: dict(c) for kind, c in census.items()},
            "overlap": overlap,
            "fuse_steps": k,
        }
    except Exception as e:  # noqa: BLE001 - telemetry must never kill a run
        logger.debug(f"telemetry: static step cost unavailable: {e!r}")
        return None


def joined_rates(static: Dict[str, Any], steps_per_sec: float,
                 peak_flops: float,
                 interconnect_bytes_per_sec: float = 0.0) -> Dict[str, float]:
    """Price the static per-step costs at the observed rate."""
    out = {
        "modeled_comm_bytes_per_sec":
            static["comm_bytes_per_step"] * steps_per_sec,
    }
    if static.get("modeled_peak_hbm"):
        # not a rate, but it rides the same window join so every consumer
        # (bench, dryrun, monitors) sees modeled peak next to measured
        out["modeled_peak_hbm"] = float(static["modeled_peak_hbm"])
    if static.get("flops_per_step") and peak_flops > 0:
        out["window_mfu"] = (static["flops_per_step"] * steps_per_sec
                             / peak_flops)
    exposed = static.get("exposed_comm_bytes_per_step")
    if exposed is not None and interconnect_bytes_per_sec > 0:
        # modeled serial wire time of the exposed collectives per step —
        # the comm the scheduler is NOT hiding behind compute
        out["exposed_comm_ms"] = exposed / interconnect_bytes_per_sec * 1e3
    total = static.get("comm_bytes_per_step") or 0
    if total and "overlapped_comm_bytes_per_step" in static:
        out["overlap_efficiency"] = (
            static["overlapped_comm_bytes_per_step"] / total)
    return out
