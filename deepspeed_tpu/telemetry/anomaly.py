"""Anomaly detection over drained telemetry windows.

Reference analogue: none — DeepSpeed logs raw scalars and leaves spike
hunting to the human reading TensorBoard. Here the window statistics the
accumulators already produce (zero extra syncs) are compared against
exponential moving baselines, and violations become STRUCTURED events with a
severity, fanned out through MonitorMaster and the JSONL sink.

Rules (all thresholds in config section ``telemetry.anomaly``):

  * ``loss_spike``       — window loss mean > factor x EMA baseline
                            (non-finite loss is always critical)
  * ``gnorm_drift``      — window grad-norm mean drifts a factor above OR
                            below its EMA baseline (non-finite -> critical)
  * ``overflow_burst``   — fp16 overflow rate in the window >= burst rate
                            (no warmup: a burst is a burst)
  * ``dispatch_stall``   — host ``block`` time per step regresses a factor
                            above its EMA baseline (the async pipeline lost
                            its overlap: input starvation, a new sync, a
                            slower program)

Baselines update every window with EMA(alpha); the first ``warmup_windows``
windows only seed baselines and never fire relative rules.
"""

import math
from typing import Any, Dict, List

SEVERITY_NUM = {"info": 0, "warning": 1, "critical": 2}


def severity_num(severity: str) -> int:
    return SEVERITY_NUM.get(severity, 1)


class AnomalyDetector:
    def __init__(self, cfg):
        self.cfg = cfg
        self._ema: Dict[str, float] = {}
        self._windows = 0

    def _update(self, key: str, value: float) -> None:
        if not math.isfinite(value):
            return  # a poisoned baseline would mask every later anomaly
        alpha = self.cfg.ema_alpha
        prev = self._ema.get(key)
        self._ema[key] = value if prev is None else \
            alpha * value + (1.0 - alpha) * prev

    def baseline(self, key: str):
        return self._ema.get(key)

    def observe(self, window: Dict[str, Any], step: int) -> List[Dict[str, Any]]:
        """Evaluate every rule against one drained window; returns the
        structured events (possibly empty) and folds the window into the
        EMA baselines."""
        cfg = self.cfg
        events: List[Dict[str, Any]] = []
        warm = self._windows >= cfg.warmup_windows

        def fire(rule, severity, value, baseline, threshold, message):
            events.append({
                "type": "anomaly", "rule": rule, "severity": severity,
                "step": int(step), "value": float(value),
                "baseline": None if baseline is None else float(baseline),
                "threshold": float(threshold), "message": message,
            })

        applied = int(window.get("applied", 0) or 0)
        loss = float(window.get("loss_mean", 0.0) or 0.0)
        gnorm = float(window.get("gnorm_mean", 0.0) or 0.0)

        if applied > 0:
            if not math.isfinite(loss):
                fire("loss_spike", "critical", loss, self.baseline("loss"),
                     float("inf"), f"window loss mean is non-finite ({loss})")
            else:
                base = self.baseline("loss")
                if warm and base is not None:
                    thr = cfg.loss_spike_factor * abs(base) + 1e-12
                    if abs(loss) > thr:
                        sev = ("critical"
                               if abs(loss) > 2 * cfg.loss_spike_factor
                               * abs(base) + 1e-12 else "warning")
                        fire("loss_spike", sev, loss, base, thr,
                             f"window loss mean {loss:.4g} exceeds "
                             f"{cfg.loss_spike_factor:g}x baseline "
                             f"{base:.4g}")

            if not math.isfinite(gnorm):
                fire("gnorm_drift", "critical", gnorm,
                     self.baseline("gnorm"), float("inf"),
                     f"window grad-norm mean is non-finite ({gnorm})")
            else:
                base = self.baseline("gnorm")
                if warm and base is not None and base > 0:
                    hi = cfg.gnorm_drift_factor * base
                    lo = base / cfg.gnorm_drift_factor
                    if gnorm > hi or (gnorm > 0 and gnorm < lo):
                        fire("gnorm_drift", "warning", gnorm, base,
                             hi if gnorm > hi else lo,
                             f"window grad-norm mean {gnorm:.4g} drifted "
                             f"{cfg.gnorm_drift_factor:g}x from baseline "
                             f"{base:.4g}")

        rate = float(window.get("overflow_rate", 0.0) or 0.0)
        if int(window.get("steps", 0) or 0) > 0 \
                and rate >= cfg.overflow_burst_rate:
            fire("overflow_burst", "critical", rate, None,
                 cfg.overflow_burst_rate,
                 f"{window.get('overflows', 0)} overflow-skipped of "
                 f"{window.get('steps', 0)} steps "
                 f"({rate:.0%} >= {cfg.overflow_burst_rate:.0%}) — the loss "
                 "scale is thrashing or the model diverged")

        stall = window.get("stall_ms_per_step")
        if stall is not None:
            base = self.baseline("stall")
            if warm and base is not None and \
                    stall > cfg.stall_regression_factor * base + 1e-3:
                fire("dispatch_stall", "warning", stall, base,
                     cfg.stall_regression_factor * base,
                     f"host blocked {stall:.2f} ms/step on in-flight steps "
                     f"vs baseline {base:.2f} — the async pipeline lost its "
                     "overlap")
            self._update("stall", float(stall))

        if applied > 0:
            self._update("loss", loss)
            self._update("gnorm", gnorm)
        self._windows += 1
        return events
