"""In-graph metric accumulators for the telemetry subsystem.

Reference analogue: none as one piece — DeepSpeed's monitoring is an eager
fan-out of per-step host scalars (``monitor/monitor.py`` MonitorMaster fed by
``engine._write_monitor_events``), which costs one device sync per metric per
step. This runtime's hot loop (PR 2) performs NO steady-state host sync
besides the single batched ``device_get`` in ``engine._log_step`` at
``steps_per_print`` boundaries, so richer statistics must be computed *on
device, inside the jitted step*.

Design: the accumulators are CUMULATIVE counters living in a donated
``state["telemetry"]`` leaf, advanced by :func:`accumulate` with a handful of
scalar ops (plus one ``[n_buckets]`` one-hot add for the grad-norm
log-histogram). There is no in-graph reset and no extra dispatch: the host
derives per-window statistics by DIFFING two consecutive drained snapshots
(:func:`window_stats`). Running maxima are cumulative by construction.

Host-driven optimizer paths (NVMe swapper, layer-streamed ZeRO-Infinity)
never run a jitted optimizer apply, so they mirror the same leaf host-side
(:class:`HostWindow`): their per-step metric scalars queue *un-fetched* and
are folded in by the same single batched ``device_get`` at the window
boundary.
"""

import math
from typing import Any, Dict, List, Optional

import numpy as np

# grad-norm log2 histogram: bucket 0 collects everything below 2**HIST_LOG2_MIN,
# interior bucket k (1..n-2) covers [2^(HIST_LOG2_MIN+k-1), 2^(HIST_LOG2_MIN+k)),
# and the last bucket everything >= 2**(HIST_LOG2_MIN + n_buckets - 2); with the
# defaults (16 buckets) the interior spans [2^-8, 2^6). Overflow steps don't
# contribute at all — their loss-scale-saturated norms carry no signal.
HIST_BUCKETS = 16
HIST_LOG2_MIN = -8

_FLOAT_KEYS = ("loss_sum", "loss_max", "gnorm_sum", "gnorm_max",
               "ratio_sum", "ratio_max")
_INT_KEYS = ("steps", "overflows")


def init_leaf(n_buckets: int = HIST_BUCKETS) -> Dict[str, Any]:
    """Fresh cumulative accumulator leaf (all replicated scalars + one
    ``[n_buckets]`` int32 histogram). Lives in the donated jitted state."""
    import jax.numpy as jnp
    return {
        "steps": jnp.zeros((), jnp.int32),
        "overflows": jnp.zeros((), jnp.int32),
        "loss_sum": jnp.zeros((), jnp.float32),
        "loss_max": jnp.full((), -jnp.inf, jnp.float32),
        "gnorm_sum": jnp.zeros((), jnp.float32),
        "gnorm_max": jnp.zeros((), jnp.float32),
        "gnorm_hist": jnp.zeros((n_buckets,), jnp.int32),
        "ratio_sum": jnp.zeros((), jnp.float32),
        "ratio_max": jnp.zeros((), jnp.float32),
    }


def update_to_param_ratio(new_params, params):
    """Global ||update|| / ||param|| of one optimizer step, in f32. On an
    overflow-skipped step ``new_params == params`` and the ratio is 0."""
    import jax
    import jax.numpy as jnp
    n_leaves = jax.tree.leaves(new_params)
    o_leaves = jax.tree.leaves(params)
    d2 = sum(jnp.sum(jnp.square(n.astype(jnp.float32) - o.astype(jnp.float32)))
             for n, o in zip(n_leaves, o_leaves))
    p2 = sum(jnp.sum(jnp.square(o.astype(jnp.float32))) for o in o_leaves)
    return jnp.sqrt(d2) / (jnp.sqrt(p2) + 1e-12)


def accumulate(tel: Dict[str, Any], *, loss, gnorm, overflow,
               update_ratio=None) -> Dict[str, Any]:
    """One jitted-step advance of the cumulative leaf. All inputs are traced
    scalars the step already computed — no new reductions over model-sized
    tensors happen here (``update_ratio`` is the caller's, see
    :func:`update_to_param_ratio`). Overflow steps count into ``steps`` and
    ``overflows`` but are excluded from the value statistics: their
    loss/grads are loss-scale saturated garbage."""
    import jax
    import jax.numpy as jnp
    loss = jnp.asarray(loss, jnp.float32)
    gnorm = jnp.asarray(gnorm, jnp.float32)
    ok = jnp.logical_not(overflow)
    okf = ok.astype(jnp.float32)
    n_buckets = tel["gnorm_hist"].shape[0]
    bucket = jnp.clip(
        jnp.floor(jnp.log2(jnp.maximum(gnorm, jnp.float32(1e-30))))
        - (HIST_LOG2_MIN - 1),
        0, n_buckets - 1).astype(jnp.int32)
    new = dict(tel)
    new["steps"] = tel["steps"] + 1
    new["overflows"] = tel["overflows"] + overflow.astype(jnp.int32)
    new["loss_sum"] = tel["loss_sum"] + okf * loss
    new["loss_max"] = jnp.where(ok, jnp.maximum(tel["loss_max"], loss),
                                tel["loss_max"])
    new["gnorm_sum"] = tel["gnorm_sum"] + okf * gnorm
    new["gnorm_max"] = jnp.where(ok, jnp.maximum(tel["gnorm_max"], gnorm),
                                 tel["gnorm_max"])
    new["gnorm_hist"] = tel["gnorm_hist"] + jnp.where(
        ok, jax.nn.one_hot(bucket, n_buckets, dtype=jnp.int32),
        jnp.zeros((n_buckets,), jnp.int32))
    if update_ratio is not None:
        ratio = jnp.asarray(update_ratio, jnp.float32)
        new["ratio_sum"] = tel["ratio_sum"] + okf * ratio
        new["ratio_max"] = jnp.where(
            ok, jnp.maximum(tel["ratio_max"], ratio), tel["ratio_max"])
    return new


def window_stats(cur: Dict[str, Any],
                 prev: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Per-window statistics from two consecutive drained (host) snapshots
    of the cumulative leaf. ``prev=None`` means 'since start'. Maxima are
    running (cumulative) — the ISSUE contract — sums/counts/histogram are
    windowed deltas."""
    def _i(snap, k):
        return int(np.asarray(snap[k])) if snap is not None else 0

    def _f(snap, k):
        return float(np.asarray(snap[k])) if snap is not None else 0.0

    steps = _i(cur, "steps") - _i(prev, "steps")
    overflows = _i(cur, "overflows") - _i(prev, "overflows")
    applied = max(0, steps - overflows)
    # the leaf seeds loss_max at -inf; before any applied step that's "no
    # data", not a value — None keeps it out of scalar sinks (events filter
    # on `is not None`; the JSONL sink nulls non-finite floats anyway)
    loss_max = _f(cur, "loss_max")
    hist_cur = np.asarray(cur["gnorm_hist"], dtype=np.int64)
    hist_prev = (np.asarray(prev["gnorm_hist"], dtype=np.int64)
                 if prev is not None else np.zeros_like(hist_cur))
    out = {
        "steps": steps,
        "applied": applied,
        "overflows": overflows,
        "overflow_rate": overflows / steps if steps else 0.0,
        "loss_mean": ((_f(cur, "loss_sum") - _f(prev, "loss_sum")) / applied
                      if applied else 0.0),
        "loss_max": loss_max if math.isfinite(loss_max) else None,
        "gnorm_mean": ((_f(cur, "gnorm_sum") - _f(prev, "gnorm_sum")) / applied
                       if applied else 0.0),
        "gnorm_max": _f(cur, "gnorm_max"),
        "update_ratio_mean": ((_f(cur, "ratio_sum") - _f(prev, "ratio_sum"))
                              / applied if applied else 0.0),
        "update_ratio_max": _f(cur, "ratio_max"),
        "gnorm_hist": (hist_cur - hist_prev).tolist(),
    }
    return out


class HostWindow:
    """Host-side mirror of the device accumulator leaf for the host-driven
    executors (NVMe swapper, layer-streamed infinity). ``add`` queues the
    step's metric scalars WITHOUT fetching them; the engine fetches the
    pending list inside its one batched ``device_get`` and folds it in via
    ``drain``, which returns a cumulative snapshot shaped exactly like a
    drained device leaf — so ``window_stats`` works unchanged."""

    def __init__(self, n_buckets: int = HIST_BUCKETS):
        self.n_buckets = n_buckets
        self._pending: List[Dict[str, Any]] = []
        self._cum = {
            "steps": 0, "overflows": 0,
            "loss_sum": 0.0, "loss_max": -math.inf,
            "gnorm_sum": 0.0, "gnorm_max": 0.0,
            "gnorm_hist": np.zeros((n_buckets,), np.int64),
            "ratio_sum": 0.0, "ratio_max": 0.0,
        }

    def add(self, metrics: Dict[str, Any]) -> None:
        self._pending.append({k: metrics[k]
                              for k in ("loss", "grad_norm", "overflow")
                              if k in metrics})

    def pending(self) -> List[Dict[str, Any]]:
        """The un-fetched queue, for inclusion in the engine's batched
        device_get (device scalars pass through jax.device_get; host floats
        come back unchanged)."""
        return list(self._pending)

    def drain(self, fetched: Optional[List[Dict[str, Any]]]) -> Dict[str, Any]:
        self._pending = []
        c = self._cum
        for m in fetched or []:
            ov = bool(np.asarray(m.get("overflow", False)))
            c["steps"] += 1
            if ov:
                c["overflows"] += 1
                continue
            loss = float(np.asarray(m.get("loss", 0.0)))
            gnorm = float(np.asarray(m.get("grad_norm", 0.0)))
            c["loss_sum"] += loss
            c["loss_max"] = max(c["loss_max"], loss)
            c["gnorm_sum"] += gnorm
            c["gnorm_max"] = max(c["gnorm_max"], gnorm)
            b = int(np.clip(math.floor(math.log2(max(gnorm, 1e-30)))
                            - (HIST_LOG2_MIN - 1), 0, self.n_buckets - 1))
            c["gnorm_hist"][b] += 1
        # snapshot COPY: the caller diffs consecutive snapshots
        return {k: (v.copy() if isinstance(v, np.ndarray) else v)
                for k, v in c.items()}
