from deepspeed_tpu.elasticity.elasticity import (
    ElasticityError, compute_elastic_config, get_compatible_gpus)
from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
from deepspeed_tpu.elasticity.rendezvous import FileRendezvous, reform_step
