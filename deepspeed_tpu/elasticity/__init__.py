from deepspeed_tpu.elasticity.elasticity import (
    ElasticityError, compute_elastic_config, get_compatible_gpus)
from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
from deepspeed_tpu.elasticity.rendezvous import FileRendezvous, reform_step
# re-exported for the preemption-recovery loop (README "Fault tolerance"):
# install a PreemptionHandler, pass it to DSElasticAgent, catch Preempted
from deepspeed_tpu.robustness.preemption import Preempted, PreemptionHandler
