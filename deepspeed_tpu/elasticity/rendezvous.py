"""File-store rendezvous: multi-host elastic membership + host-death
recovery.

Reference: the torchelastic rendezvous underneath
``deepspeed/elasticity/elastic_agent.py:25`` (DSElasticAgent) — a shared
store (etcd/c10d) tracks worker liveness via heartbeats; on a membership
change the survivors agree on a NEW generation and restart training at the
new world size from the last checkpoint.

TPU-native re-design: the store is a shared directory (TPU pods already
mount one for checkpoints — NFS/gcsfuse), so no extra service:

- every host writes a ``hb_<host>.json`` heartbeat (monotonic counter +
  wall time); a host whose heartbeat is older than ``dead_after_s`` is
  dead — this is how a WHOLE-HOST failure is detected, which the per-chip
  device probe (elastic_agent.probe_devices) cannot see;
- the deterministic leader (lexicographically-first live host) publishes
  ``gen_<N>.json`` manifests: {generation, hosts, coordinator}; followers
  poll for the newest manifest;
- when the live set differs from the current manifest's hosts, the leader
  publishes the next generation; every member then rebuilds its jax
  distributed runtime against the manifest's coordinator and resumes from
  the latest checkpoint with the batch plan for the new world
  (elasticity.compute_elastic_config — same contract as the reference's
  restart-from-checkpoint).

Deterministic and unit-testable: time is injectable, and multiple "hosts"
are simulated as distinct host_ids over one store directory.
"""

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

# Wire-schema versions for the two payloads this module writes. Defined
# HERE (not in inference/schemas.py, which re-exports them) because the
# inference package's __init__ imports the router, which imports this
# module — a module-level import the other way would be a cycle.
HEARTBEAT_SCHEMA = 1
GENERATION_MANIFEST_SCHEMA = 1


class FileRendezvous:
    """One participant's view of the membership store."""

    def __init__(self, store_dir: str, host: str, *,
                 coordinator_port: int = 8476,
                 dead_after_s: float = 15.0,
                 clock: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        self.store = store_dir
        self.host = host
        self.port = coordinator_port
        self.dead_after = dead_after_s
        self._clock = clock or time.time
        # sleep must come from the same time source as the deadline checks:
        # a full poll_s time.sleep under an injected fake clock either hangs
        # (clock never advances) or insta-times-out (clock jumped past the
        # deadline). With a fake clock and no injected sleep, yield a bounded
        # 1ms real sleep per poll — the deadline logic stays on the fake
        # clock, but the loop cannot busy-spin a core (or hammer the store
        # with heartbeats) while another thread advances time.
        if sleep is not None:
            self._sleep = sleep
        elif clock is None:
            self._sleep = time.sleep
        else:
            self._sleep = lambda s: time.sleep(min(s, 0.001))
        self._beats = 0
        self._seen_gen = -1   # newest generation this member has acted on
        os.makedirs(store_dir, exist_ok=True)

    # -- heartbeats ----------------------------------------------------
    def _hb_path(self, host: str) -> str:
        return os.path.join(self.store, f"hb_{host}.json")

    def heartbeat(self, meta: Optional[Dict[str, Any]] = None):
        """Atomic write (tmp + rename): a torn read must not kill a host.

        ``meta`` is an optional opaque payload the host wants its peers to
        see next to its liveness (the serving router publishes queue depth
        / capacity here). The payload carries ``schema`` so readers can
        version-gate: hosts that predate the field wrote neither ``schema``
        nor ``meta``, and readers treat both as absent — old and new hosts
        interop over one store (pinned by a unit test)."""
        self._beats += 1
        payload: Dict[str, Any] = {"host": self.host, "beats": self._beats,
                                   "ts": self._clock(),
                                   "schema": HEARTBEAT_SCHEMA}
        if meta is not None:
            payload["meta"] = dict(meta)
        tmp = self._hb_path(self.host) + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._hb_path(self.host))

    def read_heartbeats(self) -> Dict[str, Dict[str, Any]]:
        """Every readable heartbeat payload by host, with NO liveness
        filter — the router's registry cache wants stale payloads too
        (staleness there IS the health signal). Torn/partial heartbeat
        files are skipped exactly like ``.tmp.`` temps: an unreadable
        payload must never take the reader down or invent a host."""
        out: Dict[str, Dict[str, Any]] = {}
        for fn in sorted(os.listdir(self.store)):
            # atomic-write temps (hb_<host>.json.tmp.<pid>) share the hb_
            # prefix: counting one would duplicate a host (wrong world size,
            # spurious reform)
            if not fn.startswith("hb_") or ".tmp." in fn:
                continue
            try:
                with open(os.path.join(self.store, fn)) as f:
                    hb = json.load(f)
                float(hb["ts"])                    # required fields only:
                out[hb["host"]] = hb               # schema/meta optional
            except (OSError, ValueError, KeyError, TypeError):  # torn write
                continue
        return out

    def live_host_info(self) -> Dict[str, Dict[str, Any]]:
        """{host: payload} for every host whose heartbeat is fresh (within
        ``dead_after_s``), meta included when the host published one."""
        now = self._clock()
        return {h: p for h, p in self.read_heartbeats().items()
                if now - float(p["ts"]) <= self.dead_after}

    def live_hosts(self) -> List[str]:
        return sorted(self.live_host_info())

    # -- generations ---------------------------------------------------
    def _gen_path(self, n: int) -> str:
        return os.path.join(self.store, f"gen_{n:08d}.json")

    def current_generation(self) -> Optional[Dict[str, Any]]:
        # gen_N.json.tmp.<pid> sorts AFTER gen_N.json: reading a torn temp
        # as "the newest manifest" would make this return None and let a
        # leader republish generation 0 over existing history
        gens = sorted(fn for fn in os.listdir(self.store)
                      if fn.startswith("gen_") and ".tmp." not in fn)
        # a torn/unreadable NEWEST manifest must not erase history either:
        # returning None there would let the leader republish generation 0
        # over existing generations (and every follower's _seen_gen
        # bookkeeping with it) — fall back to the next-newest readable one
        for fn in reversed(gens):
            try:
                with open(os.path.join(self.store, fn)) as f:
                    return json.load(f)
            except (OSError, ValueError):  # torn write: try the previous
                logger.warning(f"rendezvous: manifest {fn} unreadable; "
                               "falling back to the previous generation")
                continue
        return None

    def is_leader(self) -> bool:
        live = self.live_hosts()
        return bool(live) and live[0] == self.host

    def should_reform(self) -> bool:
        """Membership drifted from the published manifest (host died or
        rejoined) — time for a new generation."""
        cur = self.current_generation()
        live = self.live_hosts()
        if cur is None:
            return bool(live)
        return sorted(cur["hosts"]) != live

    def publish_generation(self, hosts: List[str],
                           coordinator: Optional[str] = None
                           ) -> Dict[str, Any]:
        """Publish the next generation manifest over an explicit host list.
        Registry use (the serving router's replica membership): the
        publisher needn't be a live heartbeating member — leadership is the
        CALLER's contract. ``propose_generation`` is the leader-elected
        wrapper the elastic agent uses. The next generation number comes
        from ``current_generation`` — whose torn-newest-manifest fallback
        guarantees a publisher behind a torn write continues the history
        instead of republishing generation 0 over it."""
        hosts = sorted(hosts)
        cur = self.current_generation()
        n = (cur["generation"] + 1) if cur else 0
        manifest = {"generation": n, "hosts": hosts,
                    "coordinator": coordinator or (
                        f"{hosts[0]}:{self.port}" if hosts else None),
                    "ts": self._clock(),
                    "schema": GENERATION_MANIFEST_SCHEMA}
        tmp = self._gen_path(n) + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, self._gen_path(n))
        self._seen_gen = n
        logger.info(f"rendezvous: generation {n} published — "
                    f"{len(hosts)} host(s), coordinator "
                    f"{manifest['coordinator']}")
        return manifest

    def propose_generation(self) -> Optional[Dict[str, Any]]:
        """Leader-only: publish the next generation over the live set.
        Returns the manifest (followers get it via wait_generation)."""
        if not self.is_leader():
            return None
        return self.publish_generation(self.live_hosts())

    def wait_generation(self, min_generation: int = 0,
                        timeout_s: float = 60.0,
                        poll_s: float = 0.5) -> Dict[str, Any]:
        """Block until a manifest with generation >= min_generation exists.
        Followers call this after noticing membership drift (or on join).

        Keeps heartbeating while blocked: a reform can take most of a
        minute, and a follower that goes silent for dead_after_s would be
        declared dead and excluded from the very generation it waits for."""
        deadline = self._clock() + timeout_s
        while True:
            self.heartbeat()
            cur = self.current_generation()
            if cur is not None and cur["generation"] >= min_generation:
                return cur
            if self._clock() > deadline:
                raise TimeoutError(
                    f"rendezvous: no generation >= {min_generation} within "
                    f"{timeout_s}s ({len(self.live_hosts())} live hosts)")
            self._sleep(poll_s)

    def leave(self):
        """Graceful exit: drop the heartbeat so the next round excludes us."""
        try:
            os.remove(self._hb_path(self.host))
        except OSError:
            pass

    def retire(self, host: str):
        """Remove ANOTHER host's heartbeat from the store. Only for a
        coordinator holding death evidence (the serving router after a
        decommission/failover — ISSUE 19): a retired-but-alive host
        simply re-appears on its next beat, so this can hide a live host
        for at most one heartbeat interval, never fence one out. Without
        it, autoscale cycles accumulate dead entries forever."""
        try:
            os.remove(self._hb_path(host))
        except OSError:
            pass


def reform_step(rdzv: FileRendezvous) -> Optional[Dict[str, Any]]:
    """One membership round: heartbeat; if the live set drifted from the
    manifest the leader publishes the next generation (followers wait for
    it); and ANY generation this member hasn't acted on yet is returned —
    so a follower whose leader already re-formed still learns about it on
    its next round. Returns None when nothing changed. The caller (elastic
    agent / launcher) rebuilds its jax distributed runtime against
    manifest['coordinator'] and resumes from the latest checkpoint with
    the new world's batch plan."""
    rdzv.heartbeat()
    published = None
    if rdzv.should_reform():
        cur = rdzv.current_generation()
        want = (cur["generation"] + 1) if cur else 0
        if rdzv.is_leader():
            published = rdzv.propose_generation()
        else:
            rdzv.wait_generation(min_generation=want)
    if published is not None:
        return published
    newest = rdzv.current_generation()
    if newest is not None and newest["generation"] > rdzv._seen_gen:
        rdzv._seen_gen = newest["generation"]
        return newest
    return None
