"""Elastic agent: watch the device world, rescale, resume.

Reference: ``deepspeed/elasticity/elastic_agent.py:25`` (DSElasticAgent over
torchelastic rendezvous: restarts workers on membership changes) +
``launcher/launch.py``'s elastic branch.

TPU-native re-design: there is no per-GPU process tree to restart — one
process drives the whole mesh, so a scale event is handled IN-PROCESS: the
agent notices the device count changed, re-runs ``compute_elastic_config``
for the new world, rebuilds the engine over the surviving devices, and
resumes from the latest checkpoint (which is elastic by construction —
Orbax restores into any mesh). Periodic checkpoints bound the replayed
work, mirroring the reference's "restart from last checkpoint" contract.
"""

import copy
from typing import Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import logger


class DSElasticAgent:
    """Drives training through device-count changes.

    model_factory: () -> ModelSpec (a fresh spec per engine build).
    config: the engine config DICT with an enabled ``elasticity`` section;
    the agent owns the batch triad (train/micro/gas are derived per world).
    checkpoint_interval: save every N optimizer steps so a scale event
    loses at most N steps.
    device_count_fn: override for tests (simulate 8 -> 4 devices).
    """

    def __init__(self, model_factory: Callable, config: Dict, ckpt_dir: str,
                 *, checkpoint_interval: int = 10,
                 device_count_fn: Optional[Callable[[], int]] = None):
        if not config.get("elasticity", {}).get("enabled"):
            raise ValueError("DSElasticAgent requires an enabled "
                             "'elasticity' config section")
        self._factory = model_factory
        self._base_config = copy.deepcopy(config)
        self._ckpt_dir = ckpt_dir
        self._interval = max(1, checkpoint_interval)
        self._device_fn = device_count_fn or (lambda: jax.device_count())
        self.engine = None
        self.world = 0
        self.scale_events = 0
        self._ensure_engine()

    # ------------------------------------------------------------------
    def _ensure_engine(self) -> bool:
        """(Re)build the engine if the device world changed. Returns True
        when a rescale happened."""
        world = int(self._device_fn())
        if self.engine is not None and world == self.world:
            return False
        rescaled = self.engine is not None
        if rescaled:
            logger.warning(f"elastic agent: world size {self.world} -> "
                           f"{world}; rebuilding from latest checkpoint")
            # quiesce the old engine's async checkpoint writer BEFORE the
            # new engine reads 'latest' — otherwise the load can race a
            # partially-written save
            self.engine.wait_checkpoint()
            self.scale_events += 1
        import deepspeed_tpu
        # initialize() re-runs compute_elastic_config for THIS world and
        # derives the train/micro/gas triad itself
        engine, *_ = deepspeed_tpu.initialize(
            model=self._factory(), config=copy.deepcopy(self._base_config),
            devices=jax.devices()[:world])
        try:
            engine.load_checkpoint(self._ckpt_dir)
            logger.info(f"elastic agent: resumed at step "
                        f"{engine.global_steps} with world={world}")
        except FileNotFoundError:
            logger.info(f"elastic agent: fresh start with world={world}")
        self.engine = engine
        self.world = world
        return rescaled

    # ------------------------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.engine.config.train_batch_size

    def train_batch(self, batch) -> Dict:
        """One global step; transparently rescales between steps. `batch`
        may be a callable(batch_size) -> batch so the agent can request the
        right global batch after a rescale."""
        self._ensure_engine()
        if callable(batch):
            batch = batch(self.batch_size)
        metrics = self.engine.train_batch(batch)
        if self.engine.global_steps % self._interval == 0:
            self.engine.save_checkpoint(self._ckpt_dir)
        return metrics

    def save(self):
        self.engine.save_checkpoint(self._ckpt_dir)
