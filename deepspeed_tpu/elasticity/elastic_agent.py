"""Elastic agent: watch the device world, rescale, resume.

Reference: ``deepspeed/elasticity/elastic_agent.py:25`` (DSElasticAgent over
torchelastic rendezvous: restarts workers on membership changes) +
``launcher/launch.py``'s elastic branch.

TPU-native re-design: there is no per-GPU process tree to restart — one
process drives the whole mesh, so a scale event is handled IN-PROCESS: the
agent notices the device count changed, re-runs ``compute_elastic_config``
for the new world, rebuilds the engine over the surviving devices, and
resumes from the latest checkpoint (which is elastic by construction —
Orbax restores into any mesh). Periodic checkpoints bound the replayed
work, mirroring the reference's "restart from last checkpoint" contract.
"""

import copy
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.preemption import Preempted
from deepspeed_tpu.utils.logging import logger


def probe_devices(devices=None) -> List:
    """Health-check each device with a tiny compute + fetch; return the
    healthy ones. The fetch is the real test: through some transports a
    dead chip only surfaces on device->host reads (reference analogue:
    torchelastic's worker liveness watch, elastic_agent.py:25 — there a
    process heartbeat, here a per-chip probe since one process drives all
    chips)."""
    devices = list(devices if devices is not None else jax.devices())
    healthy = []
    # Build the probe host-side: jnp.ones would materialize on the DEFAULT
    # device first, so if the default device is the dead chip every probe
    # would fail during array creation and the survivors would be reported
    # unhealthy too.
    probe = np.ones((8,), np.float32)
    for d in devices:
        try:
            x = jax.device_put(probe, d)
            if float(jax.device_get(jnp.sum(x + 1.0))) == 16.0:
                healthy.append(d)
            else:  # pragma: no cover - wrong math = sick chip
                logger.warning(f"elastic agent: device {d} failed the "
                               "probe value check")
        except Exception as e:  # noqa: BLE001 - any fault marks it dead
            logger.warning(f"elastic agent: device {d} unhealthy: {e}")
    return healthy


class DSElasticAgent:
    """Drives training through device-count changes.

    model_factory: () -> ModelSpec (a fresh spec per engine build).
    config: the engine config DICT with an enabled ``elasticity`` section;
    the agent owns the batch triad (train/micro/gas are derived per world).
    checkpoint_interval: save every N optimizer steps so a scale event
    loses at most N steps.
    device_count_fn: override for tests (simulate 8 -> 4 devices).
    """

    def __init__(self, model_factory: Callable, config: Dict, ckpt_dir: str,
                 *, checkpoint_interval: int = 10,
                 device_count_fn: Optional[Callable[[], int]] = None,
                 probe_interval: Optional[int] = 100,
                 health_fn: Optional[Callable[[], List]] = None,
                 fault_injector=None, preemption=None):
        """probe_interval: run the device-health probe every N steps
        (default 100; the probe is ALSO the only path that scales the
        world back UP after a recovery — None disables it and the agent
        then only reacts to shrinks and failed steps). health_fn:
        override for tests / fault injection; returns the healthy
        devices. fault_injector: a robustness.FaultInjector driving the
        step/probe seams (defaults to the process-global injector armed by
        the `robustness.faults` config). preemption: a PreemptionHandler;
        when its SIGTERM latch is set, the next train_batch saves a final
        checkpoint and raises Preempted (the checkpoint-and-exit
        contract)."""
        if not config.get("elasticity", {}).get("enabled"):
            raise ValueError("DSElasticAgent requires an enabled "
                             "'elasticity' config section")
        self._factory = model_factory
        self._base_config = copy.deepcopy(config)
        self._ckpt_dir = ckpt_dir
        self._interval = max(1, checkpoint_interval)
        self._device_fn = device_count_fn or (lambda: jax.device_count())
        self._health_fn = health_fn
        self._probe_interval = probe_interval
        self._steps_since_probe = 0
        self._injector = fault_injector
        self._preemption = preemption
        self.engine = None
        self.world = 0
        self.scale_events = 0
        self.failure_events = 0
        self.ckpt_failures = 0
        self._ensure_engine()

    # ------------------------------------------------------------------
    def _fault_injector(self):
        return self._injector if self._injector is not None \
            else rb_faults.active()

    def _healthy_devices(self) -> List:
        if self._health_fn is not None:
            devices = list(self._health_fn())
        else:
            devices = probe_devices(jax.devices()[:int(self._device_fn())])
        inj = self._fault_injector()
        if inj is not None:
            devices = inj.cull(devices)
        return devices

    # ------------------------------------------------------------------
    def _ensure_engine(self, probe: bool = False) -> bool:
        """(Re)build the engine if the device world changed. Returns True
        when a rescale happened. probe=False uses the cheap device-count
        check (per step); probe=True runs the per-chip health probe (on
        the probe_interval cadence and after a failed step — probing every
        step would cost a host round trip per chip)."""
        if probe or self.engine is None:
            devices = self._healthy_devices()
        else:
            # cheap per-step check: only a SHRINK of the visible device
            # world forces a rebuild here; growth (or a recovered chip)
            # waits for the next probe — otherwise a step after a probed
            # cull would immediately scale back onto the sick chips
            avail = list(jax.devices()[:int(self._device_fn())])
            if len(avail) >= self.world:
                return False
            devices = avail
        world = len(devices)
        if world == 0:
            raise RuntimeError("elastic agent: no healthy devices remain")
        if self.engine is not None and world == self.world:
            return False
        rescaled = self.engine is not None
        if rescaled:
            logger.warning(f"elastic agent: world size {self.world} -> "
                           f"{world}; rebuilding from latest checkpoint")
            # quiesce the old engine's async checkpoint writer BEFORE the
            # new engine reads 'latest' — otherwise the load can race a
            # partially-written save
            self.engine.wait_checkpoint()
            self.scale_events += 1
            # drop the old engine BEFORE building the new one: both alive
            # at once would double device-memory residency mid-recovery
            self.engine = None
        import deepspeed_tpu
        # initialize() re-runs compute_elastic_config for THIS world and
        # derives the train/micro/gas triad itself
        engine, *_ = deepspeed_tpu.initialize(
            model=self._factory(), config=copy.deepcopy(self._base_config),
            devices=devices)
        try:
            engine.load_checkpoint(self._ckpt_dir)
            logger.info(f"elastic agent: resumed at step "
                        f"{engine.global_steps} with world={world}")
        except FileNotFoundError:
            logger.info(f"elastic agent: fresh start with world={world}")
        self.engine = engine
        self.world = world
        return rescaled

    # ------------------------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.engine.config.train_batch_size

    def train_batch(self, batch) -> Dict:
        """One global step; transparently rescales between steps and
        recovers from a step that faults (dead chip mid-run): the probe
        culls unhealthy devices, the engine rebuilds over the survivors
        from the latest checkpoint, and the step is retried ONCE. `batch`
        may be a callable(batch_size) -> batch so the agent can request
        the right global batch after a rescale."""
        if self._preemption is not None and self._preemption.requested:
            # SIGTERM latched: checkpoint-and-exit. The save is the whole
            # point — let a save failure propagate rather than exiting
            # with unsaved work
            path = self.engine.save_checkpoint(self._ckpt_dir)
            self._preemption.acknowledge(self.engine.global_steps, path)
            raise Preempted(
                f"preempted: checkpointed at step "
                f"{self.engine.global_steps}; exiting",
                step=self.engine.global_steps, ckpt_path=path)
        probe_due = (self._probe_interval is not None
                     and self._steps_since_probe >= self._probe_interval)
        if probe_due:
            self._steps_since_probe = 0
        self._ensure_engine(probe=probe_due)
        for attempt in (0, 1):
            b = batch(self.batch_size) if callable(batch) else batch
            try:
                inj = self._fault_injector()
                if inj is not None:
                    # the step seam: scheduled device faults surface here
                    # exactly like a chip loss (a raised step); scheduled
                    # preemptions deliver a real SIGTERM
                    inj.step(self.engine.global_steps + 1)
                metrics = self.engine.train_batch(b)
                break
            except Exception as e:  # noqa: BLE001 - chip faults surface
                if attempt:          # as runtime errors from the step
                    raise
                survivors = self._healthy_devices()
                if len(survivors) >= self.world:
                    # every device is healthy: this is a software error
                    # (bad batch, NaN guard, bug), not a chip fault —
                    # silently replaying from the checkpoint would hide it
                    raise
                self.failure_events += 1
                prev_world = self.world
                logger.warning(f"elastic agent: step failed ({e}); "
                               f"{len(survivors)}/{prev_world} devices "
                               "healthy — rebuilding from the latest "
                               "checkpoint")
                try:
                    # quiesce any in-flight async save BEFORE the rebuilt
                    # engine reads 'latest' (same race the rescale path
                    # guards against)
                    self.engine.wait_checkpoint()
                except Exception:  # noqa: BLE001 - the engine may be dead
                    pass
                self.engine = None   # free it before the rebuild
                self._ensure_engine(probe=True)
                if self.world != prev_world:
                    self.scale_events += 1  # fault-driven shrink counts too
                rb_events.emit("fault_recovered", kind="device",
                               step=self.engine.global_steps,
                               prev_world=prev_world, world=self.world,
                               error=str(e))
        self._steps_since_probe += 1
        if self.engine.global_steps % self._interval == 0:
            try:
                self.engine.save_checkpoint(self._ckpt_dir)
            except OSError as e:
                # a failed PERIODIC save must not kill training: the
                # previous good tag still bounds the replay window, and
                # the integrity chain guarantees the torn attempt is never
                # loaded. Leave the failure on the telemetry stream.
                self.ckpt_failures += 1
                logger.warning("elastic agent: periodic checkpoint failed "
                               f"({e}); continuing — previous good tag "
                               "still bounds replay")
                rb_events.emit("ckpt_save_failed",
                               step=self.engine.global_steps, error=str(e))
        return metrics

    def save(self):
        self.engine.save_checkpoint(self._ckpt_dir)
