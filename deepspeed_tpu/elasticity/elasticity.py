"""Elastic training: batch-size/world-size co-design.

Reference: ``deepspeed/elasticity/elasticity.py:231``
(compute_elastic_config — picks a global batch size compatible with the
widest range of GPU counts, given candidate micro-batch sizes and a max
acceptable batch) and ``elastic_agent.py`` (the torch elastic rendezvous
driver).

TPU-native scoping: the scheduling half (rendezvous, scale events) belongs
to the cluster layer (GKE/Borg restart the job; our checkpoints are
elastic-by-construction — test_elastic_restore_across_zero_stage proves a
stage-0 save restores into stage-3 on a different mesh). What remains
load-bearing is the batch arithmetic below, which initialize() runs when
`elasticity.enabled` to pin a chip-count-compatible global batch.
"""

from typing import Dict, List, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger


class ElasticityError(Exception):
    pass


def _candidate_batches(micro_batches: Sequence[int], max_batch: int
                       ) -> List[int]:
    """Highly-divisible candidates: for each micro batch, powers-of-two and
    small-composite multiples up to max_batch (reference:
    _get_candidate_batch_sizes uses HCN multiples the same way)."""
    base = [1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256,
            384, 512, 768, 1024, 1536, 2048]
    out = set()
    for mbs in micro_batches:
        for m in base:
            if mbs * m <= max_batch:
                out.add(mbs * m)
    if not out:
        raise ElasticityError(
            f"no candidate batch size fits max_train_batch_size={max_batch} "
            f"with micro_batch_sizes={list(micro_batches)}")
    return sorted(out)


def get_compatible_gpus(batch: int, micro_batches: Sequence[int],
                        min_gpus: int, max_gpus: int) -> List[int]:
    """Device counts that can run `batch` exactly: batch % (g * mbs) == 0
    for some micro batch (reference: _get_compatible_gpus_v01)."""
    out = []
    for g in range(min_gpus, max_gpus + 1):
        if any(batch % (g * mbs) == 0 for mbs in micro_batches):
            out.append(g)
    return out


def compute_elastic_config(elastic_cfg: Dict, world_size: int = 0
                           ) -> Tuple[int, List[int], int]:
    """Pick (final_batch_size, valid_gpus, micro_batch_for_world_size).

    Chooses the candidate batch compatible with the MOST device counts in
    [min_gpus, max_gpus]; prefer_larger_batch breaks ties upward. When
    world_size > 0, also returns the largest micro batch that divides the
    per-replica share (raising if this world size is not compatible) —
    reference: elasticity.py:231-330.
    """
    enabled = elastic_cfg.get("enabled", False)
    if not enabled:
        raise ElasticityError("elasticity section is not enabled")
    micro = list(elastic_cfg.get("micro_batch_sizes", [2, 4, 6]))
    max_batch = int(elastic_cfg.get("max_train_batch_size", 2000))
    min_gpus = int(elastic_cfg.get("min_gpus", 1))
    max_gpus = int(elastic_cfg.get("max_gpus", 10000))
    prefer_larger = bool(elastic_cfg.get("prefer_larger_batch", True))
    if min_gpus < 1 or max_gpus < min_gpus:
        raise ElasticityError(f"bad gpu range [{min_gpus}, {max_gpus}]")
    if any(m < 1 for m in micro) or not micro:
        raise ElasticityError(f"bad micro_batch_sizes {micro}")

    best, best_gpus = None, []
    for cand in _candidate_batches(micro, max_batch):
        gpus = get_compatible_gpus(cand, micro, min_gpus,
                                   min(max_gpus, max_batch))
        better = (len(gpus) > len(best_gpus)
                  or (len(gpus) == len(best_gpus)
                      and prefer_larger and best is not None and cand > best))
        if best is None or better:
            best, best_gpus = cand, gpus
    final_batch = best

    micro_for_ws = 0
    if world_size > 0:
        if world_size not in best_gpus:
            raise ElasticityError(
                f"world size {world_size} is not compatible with elastic "
                f"batch {final_batch} (valid device counts: "
                f"{best_gpus[:16]}{'...' if len(best_gpus) > 16 else ''})")
        per = final_batch // world_size
        fits = [m for m in micro if per % m == 0]
        micro_for_ws = max(fits)
    logger.info(f"elasticity: batch={final_batch}, "
                f"{len(best_gpus)} valid device counts"
                + (f", micro={micro_for_ws} at world={world_size}"
                   if world_size else ""))
    return final_batch, best_gpus, micro_for_ws


def cli_main(argv=None) -> int:
    """``dstpu_elastic``: show the elastic batch plan for a config file
    (reference: ``bin/ds_elastic`` over compute_elastic_config)."""
    import argparse
    import json as _json

    p = argparse.ArgumentParser(
        prog="dstpu_elastic",
        description="elastic batch plan for a deepspeed_tpu config")
    import sys as _sys

    p.add_argument("config", help="JSON config file with an "
                                  "'elasticity' section")
    p.add_argument("-w", "--world-size", type=int, default=0,
                   help="also resolve the micro batch for this world size")
    a = p.parse_args(argv)
    if a.world_size < 0:
        print(f"error: invalid world size {a.world_size}", file=_sys.stderr)
        return 1
    try:
        with open(a.config) as f:
            cfg = _json.load(f)
        if not isinstance(cfg, dict):
            raise ElasticityError(
                f"config top level must be a JSON object, got "
                f"{type(cfg).__name__}")
        section = cfg.get("elasticity", cfg)
        batch, valid, micro = compute_elastic_config(section, a.world_size)
    except (ElasticityError, OSError, ValueError, TypeError,
            _json.JSONDecodeError) as e:
        print(f"error: {e}", file=_sys.stderr)
        return 1
    print(f"final train_batch_size: {batch}")
    print(f"compatible device counts: {valid}")
    if a.world_size:
        print(f"micro batch at world={a.world_size}: {micro}")
    return 0
