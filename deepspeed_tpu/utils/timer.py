"""Wall-clock and throughput timers.

Reference: ``deepspeed/utils/timer.py:32`` (``SynchronizedWallClockTimer``) and
``:136`` (``ThroughputTimer``). The reference synchronizes CUDA streams around
each timer; on TPU the equivalent is blocking on JAX async dispatch
(``jax.block_until_ready`` / ``jax.effects_barrier``), which we make optional
because it serializes the pipeline.
"""

import time
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


def _device_sync():
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class _Timer:
    def __init__(self, name: str, synchronize: bool = False):
        self.name = name
        self.synchronize = synchronize
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0  # seconds
        self._count = 0

    def start(self):
        if self.started:
            return
        if self.synchronize:
            _device_sync()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, reset: bool = False):
        if not self.started:
            return
        if self.synchronize:
            _device_sync()
        self._elapsed += time.perf_counter() - self._start
        self._count += 1
        self.started = False
        if reset:
            self._elapsed = 0.0
            self._count = 0

    def reset(self):
        self.started = False
        self._elapsed = 0.0
        self._count = 0

    def elapsed(self, reset: bool = True) -> float:
        """Elapsed time in milliseconds (matches the reference's unit)."""
        value = self._elapsed * 1000.0
        if reset:
            self.reset()
        return value

    def mean(self) -> float:
        if self._count == 0:
            return 0.0
        return self._elapsed * 1000.0 / self._count


class SynchronizedWallClockTimer:
    """Named timer registry; ``timer('name').start()/stop()`` + ``log(names)``."""

    def __init__(self, synchronize: bool = False):
        self.timers: "OrderedDict[str, _Timer]" = OrderedDict()
        self.synchronize = synchronize

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name, synchronize=self.synchronize)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: Optional[List[str]] = None, normalizer: float = 1.0,
            reset: bool = True, memory_breakdown: bool = False) -> str:
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        msg = "time (ms) | " + " | ".join(parts)
        logger.info(msg)
        return msg

    def get_mean(self, names: List[str]) -> Dict[str, float]:
        return {n: self.timers[n].mean() for n in names if n in self.timers}


class ThroughputTimer:
    """Samples/sec + time/step reporting across steps.

    Reference: ``deepspeed/utils/timer.py:136``. We keep the same skip of the
    first few steps (compile warm-up dominates on XLA far more than on CUDA).

    Async-dispatch aware: under JAX async dispatch a per-step host timestamp
    measures DISPATCH, not execution, and a per-step device sync (the old
    behavior) serializes the very pipeline the engine builds. Timing is
    therefore window-based: the timer blocks only when a window of
    ``steps_per_output`` steps closes — via ``jax.block_until_ready`` on the
    step *output* when the caller passes one to ``stop(output=...)`` — and
    reports the window-average step time. ``enabled=False`` removes even
    those syncs (pure dispatch timing / debugging).
    """

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False,
                 logging_fn=None, enabled: bool = True):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = max(1, steps_per_output)
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.enabled = enabled
        self.initialized = False
        self.global_step_count = 0
        self.local_step_count = 0
        self.total_elapsed_time = 0.0
        self.timed_steps = 0
        self._window_start = None   # perf_counter at the open window's start
        self._window_steps = 0
        self.started = False

    def update_epoch_count(self):
        self.local_step_count = 0

    def start(self):
        self.started = True
        if not self.enabled:
            return
        if self.global_step_count >= self.start_step \
                and self._window_start is None:
            _device_sync()  # anchor the first window honestly
            self._window_start = time.perf_counter()
            self._window_steps = 0

    def stop(self, global_step: bool = True, report_speed: bool = True,
             output=None, steps: int = 1):
        """Count `steps` finished dispatches (a fused K-step program passes
        steps=K). At window boundaries, block on `output` (the step's
        metrics/state) so the recorded time covers execution, not dispatch."""
        if not self.started:
            return
        self.started = False
        before = self.global_step_count
        if global_step:
            self.global_step_count += steps
            self.local_step_count += steps
        if not self.enabled or self._window_start is None:
            return
        self._window_steps += steps
        if (self.global_step_count // self.steps_per_output) == \
                (before // self.steps_per_output):
            return  # window still open: no sync, no fetch
        if output is not None:
            try:
                import jax
                jax.block_until_ready(output)
            except Exception:
                _device_sync()
        else:
            _device_sync()
        now = time.perf_counter()
        duration = now - self._window_start
        self.total_elapsed_time += duration
        self.timed_steps += self._window_steps
        if report_speed:
            self.logging(
                f"step={self.global_step_count}, "
                f"samples/sec={self.avg_samples_per_sec():.2f}, "
                f"time/step(ms)="
                f"{duration / max(1, self._window_steps) * 1000:.2f}")
        self._window_start = now
        self._window_steps = 0

    def avg_samples_per_sec(self) -> float:
        if self.timed_steps == 0 or self.total_elapsed_time == 0:
            return 0.0
        return self.batch_size * self.timed_steps / self.total_elapsed_time
