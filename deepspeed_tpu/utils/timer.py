"""Wall-clock and throughput timers.

Reference: ``deepspeed/utils/timer.py:32`` (``SynchronizedWallClockTimer``) and
``:136`` (``ThroughputTimer``). The reference synchronizes CUDA streams around
each timer; on TPU the equivalent is blocking on JAX async dispatch
(``jax.block_until_ready`` / ``jax.effects_barrier``), which we make optional
because it serializes the pipeline.
"""

import time
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


def _device_sync():
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class _Timer:
    def __init__(self, name: str, synchronize: bool = False):
        self.name = name
        self.synchronize = synchronize
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0  # seconds
        self._count = 0

    def start(self):
        if self.started:
            return
        if self.synchronize:
            _device_sync()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, reset: bool = False):
        if not self.started:
            return
        if self.synchronize:
            _device_sync()
        self._elapsed += time.perf_counter() - self._start
        self._count += 1
        self.started = False
        if reset:
            self._elapsed = 0.0
            self._count = 0

    def reset(self):
        self.started = False
        self._elapsed = 0.0
        self._count = 0

    def elapsed(self, reset: bool = True) -> float:
        """Elapsed time in milliseconds (matches the reference's unit)."""
        value = self._elapsed * 1000.0
        if reset:
            self.reset()
        return value

    def mean(self) -> float:
        if self._count == 0:
            return 0.0
        return self._elapsed * 1000.0 / self._count


class SynchronizedWallClockTimer:
    """Named timer registry; ``timer('name').start()/stop()`` + ``log(names)``."""

    def __init__(self, synchronize: bool = False):
        self.timers: "OrderedDict[str, _Timer]" = OrderedDict()
        self.synchronize = synchronize

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name, synchronize=self.synchronize)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    def log(self, names: Optional[List[str]] = None, normalizer: float = 1.0,
            reset: bool = True, memory_breakdown: bool = False) -> str:
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        msg = "time (ms) | " + " | ".join(parts)
        logger.info(msg)
        return msg

    def get_mean(self, names: List[str]) -> Dict[str, float]:
        return {n: self.timers[n].mean() for n in names if n in self.timers}


class ThroughputTimer:
    """Samples/sec + tokens/sec + TFLOPS reporting across steps.

    Reference: ``deepspeed/utils/timer.py:136``. We keep the same skip of the
    first few steps (compile warm-up dominates on XLA far more than on CUDA).
    """

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False,
                 logging_fn=None):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False
        self.global_step_count = 0
        self.local_step_count = 0
        self.total_elapsed_time = 0.0
        self._start_time = 0.0
        self.started = False

    def update_epoch_count(self):
        self.local_step_count = 0

    def start(self):
        self.started = True
        if self.global_step_count >= self.start_step:
            _device_sync()
            self._start_time = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        if global_step:
            self.global_step_count += 1
            self.local_step_count += 1
        if self.global_step_count > self.start_step and self._start_time:
            _device_sync()
            duration = time.perf_counter() - self._start_time
            self.total_elapsed_time += duration
            if report_speed and self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"step={self.global_step_count}, "
                    f"samples/sec={self.avg_samples_per_sec():.2f}, "
                    f"time/step(ms)={duration * 1000:.2f}")

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count <= self.start_step or self.total_elapsed_time == 0:
            return 0.0
        steps = self.global_step_count - self.start_step
        avg = self.total_elapsed_time / max(1, steps)
        return self.batch_size / avg
