"""Offline checkpoint -> consolidated fp32 state dict (no engine needed).

Reference: ``deepspeed/utils/zero_to_fp32.py:311,360`` — merge a dead run's
ZeRO shard files into one fp32 state_dict from the command line. TPU-native
differences: GSPMD checkpoints are already logically consolidated (Orbax
stores the global array), so "merging" means extracting the fp32 MASTER
weights — from the optimizer state, from NVMe/host swap chunks
(``optswap.npz``), or from a ZeRO-Infinity layer-chunk directory — falling
back to upcasting the model params when no master exists.

CLI:  python -m deepspeed_tpu.utils.zero_to_fp32 <ckpt_dir> <out.npz> [--tag T]
"""

import argparse
import json
import os
from typing import Dict, Optional

import numpy as np

__all__ = ["convert_zero_checkpoint_to_fp32_state_dict",
           "get_fp32_state_dict_from_zero_checkpoint"]


def _flatten(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = k if not prefix else f"{prefix}/{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        elif v is not None:
            out[key] = v
    return out


def _resolve_tag(ckpt_dir: str, tag: Optional[str]) -> str:
    if tag is not None:
        return str(tag)
    latest = os.path.join(ckpt_dir, "latest")
    if not os.path.exists(latest):
        raise FileNotFoundError(f"no 'latest' file under {ckpt_dir} and no "
                                "--tag given")
    with open(latest) as f:
        return f.read().strip()


def _to_np(tree):
    import jax
    return jax.tree.map(lambda a: np.asarray(jax.device_get(a)), tree)


def _masters_from_swap_chunks(chunks: Dict[str, np.ndarray], params
                              ) -> Dict:
    """Rebuild the fp32 master tree from flat (3, C) swap chunks. The chunk
    layout is the swapper's: leaves in jax.tree.flatten order, concatenated
    then split into fixed-size chunks (master is plane 0)."""
    import jax
    leaves, treedef = jax.tree.flatten(params)
    flat = np.concatenate([chunks[f"chunk_{i}"][0]
                           for i in range(len(chunks))])
    out, off = [], 0
    for leaf in leaves:
        size = int(np.prod(leaf.shape))
        out.append(flat[off:off + size].reshape(leaf.shape)
                   .astype(np.float32))
        off += size
    return jax.tree.unflatten(treedef, out)


def _infinity_fp32(path: str) -> Dict[str, np.ndarray]:
    """ZeRO-Infinity layer-chunk checkpoint: per-layer opt_i.bin chunks
    (master = plane 0) + the shapes manifest written alongside."""
    import ml_dtypes
    with open(os.path.join(path, "infinity_shapes.json")) as f:
        man = json.load(f)
    chunk = int(man["chunk"])
    names, shapes = man["leaf_names"], man["leaf_shapes"]
    cdir = os.path.join(path, "infinity_chunks")
    layers: Dict[str, list] = {n: [] for n in names}
    L = int(man["num_layers"])
    for i in range(L):
        p = os.path.join(cdir, f"opt_{i}.bin")
        if os.path.exists(p):
            flat = np.fromfile(p, np.float32).reshape(3, chunk)[0]
        else:  # never stepped: master == bf16 params
            bits = np.fromfile(os.path.join(cdir, f"param_{i}.bin"),
                               np.uint16)
            flat = bits.view(ml_dtypes.bfloat16).astype(np.float32)
        off = 0
        for n, shape in zip(names, shapes):
            size = int(np.prod(shape))
            layers[n].append(flat[off:off + size].reshape(shape))
            off += size
    out = {f"layers/{n}": np.stack(v) for n, v in layers.items()}
    # non-layer params: masters live in the small npz (nl_opt/*/master)
    meta_p = os.path.join(path, "infinity_meta.json")
    npz_p = os.path.join(path, "infinity_small.npz")
    with open(meta_p) as f:
        dtypes = json.load(f)["dtypes"]
    with np.load(npz_p) as z:
        for k in z.files:
            key = k.replace("__", "/")
            if key.startswith("nl_opt/") and key.endswith("/master"):
                name = key[len("nl_opt/"):-len("/master")]
                arr = z[k]
                if "bfloat16" in dtypes.get(key, ""):
                    arr = arr.view(ml_dtypes.bfloat16)
                out[name] = np.asarray(arr, np.float32)
    return out


def get_fp32_state_dict_from_zero_checkpoint(ckpt_dir: str,
                                             tag: Optional[str] = None
                                             ) -> Dict[str, np.ndarray]:
    """Flat {name: fp32 array} dict from a checkpoint directory."""
    path = os.path.join(ckpt_dir, _resolve_tag(ckpt_dir, tag))
    if os.path.exists(os.path.join(path, "infinity_shapes.json")):
        return _infinity_fp32(path)

    from deepspeed_tpu.runtime.checkpointing import OrbaxCheckpointEngine
    state = OrbaxCheckpointEngine().load(os.path.join(path, "state"))
    state = _to_np(state)
    params = state["params"]
    opt = state.get("opt")

    swap_file = os.path.join(path, "optswap.npz")
    if os.path.exists(swap_file):
        with np.load(swap_file) as z:
            masters = _masters_from_swap_chunks(
                {k: z[k] for k in z.files}, params)
    elif isinstance(opt, dict) and opt.get("master") is not None:
        masters = opt["master"]
    else:
        import jax
        masters = jax.tree.map(lambda a: np.asarray(a, np.float32), params)
    flat = _flatten(masters)
    return {k: np.asarray(v, np.float32) for k, v in flat.items()}


def convert_zero_checkpoint_to_fp32_state_dict(ckpt_dir: str,
                                               output_file: str,
                                               tag: Optional[str] = None
                                               ) -> str:
    sd = get_fp32_state_dict_from_zero_checkpoint(ckpt_dir, tag)
    if not output_file.endswith(".npz"):
        output_file += ".npz"
    np.savez(output_file, **{k.replace("/", "__"): v for k, v in sd.items()})
    total = sum(v.size for v in sd.values())
    print(f"wrote {len(sd)} fp32 tensors ({total/1e6:.1f}M params) to "
          f"{output_file}")
    return output_file


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Extract a consolidated fp32 state dict from a "
                    "deepspeed_tpu checkpoint directory (no engine needed)")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    a = p.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(a.checkpoint_dir,
                                               a.output_file, tag=a.tag)


if __name__ == "__main__":
    main()
