"""Compile-time SPMD hygiene checks — absorbed into ``deepspeed_tpu.analysis``.

This module grew into the graft-lint static-analysis subsystem
(``deepspeed_tpu/analysis/``): the fd-2 SPMD-warning capture lives in
``analysis.program``, the replicated-tensor scan in ``analysis.hlo_parse``
(promoted to a budgeted analyzer in ``analysis.analyzers``). Import from
``deepspeed_tpu.analysis`` going forward; these re-exports keep old callers
working.
"""

from deepspeed_tpu.analysis.hlo_parse import replicated_tensor_bytes
from deepspeed_tpu.analysis.program import (assert_no_spmd_replication,
                                            capture_spmd_warnings)

__all__ = ["assert_no_spmd_replication", "capture_spmd_warnings",
           "replicated_tensor_bytes"]
