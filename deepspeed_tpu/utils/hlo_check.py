"""Compile-time SPMD hygiene checks.

XLA's SPMD partitioner falls back to full replication when it cannot reshard
a tensor efficiently ("Involuntary full rematerialization",
spmd_partitioner.cc). At toy shapes that is a warning on stderr; at real
shapes it is an activation-sized all-to-all + replicate in the hot loop.
Reference analogue: DeepSpeed has no compiler to warn it — its equivalent
failure is a silent extra allreduce; here we can make the compiler's warning
a hard error.

The warning is emitted by XLA's C++ logging directly on fd 2, invisible to
Python's `warnings`/`logging`, so detection needs an fd-level capture around
compilation.
"""

import contextlib
import os
import re
import sys
import tempfile

# spmd_partitioner.cc fallback lines worth failing a build over.
_SPMD_PATTERNS = (
    "Involuntary full rematerialization",
    "involuntary full rematerialization",
)


@contextlib.contextmanager
def capture_spmd_warnings(matches: list):
    """Capture fd-2 output (XLA C++ logs) while compiling; append any SPMD
    full-rematerialization warning lines to `matches`.

    Everything captured is re-emitted to the real stderr afterwards so no
    diagnostics are swallowed. Use around `.lower().compile()` or the first
    traced call of a jitted function.
    """
    sys.stderr.flush()
    saved_fd = os.dup(2)
    with tempfile.TemporaryFile(mode="w+b") as tmp:
        os.dup2(tmp.fileno(), 2)
        try:
            yield matches
        finally:
            sys.stderr.flush()
            os.dup2(saved_fd, 2)
            os.close(saved_fd)
            tmp.seek(0)
            text = tmp.read().decode("utf-8", errors="replace")
            if text:
                sys.stderr.write(text)
                sys.stderr.flush()
            for line in text.splitlines():
                if any(p in line for p in _SPMD_PATTERNS):
                    matches.append(line)


def assert_no_spmd_replication(compile_fn, *args, **kwargs):
    """Run `compile_fn(*args, **kwargs)` (something that triggers XLA SPMD
    compilation) and raise RuntimeError if the partitioner reported an
    involuntary full rematerialization. Returns compile_fn's result."""
    matches: list = []
    with capture_spmd_warnings(matches):
        result = compile_fn(*args, **kwargs)
    if matches:
        raise RuntimeError(
            "XLA SPMD involuntary full rematerialization during compile "
            f"({len(matches)} site(s)) — a tensor is being replicated in the "
            "hot loop:\n" + "\n".join(matches[:8]))
    return result


_REPLICATED_RE = re.compile(r"sharding=\{replicated\}")
_SHAPE_RE = re.compile(r"= (f32|bf16|f16)\[([\d,]+)\]")


def replicated_tensor_bytes(hlo_text: str, min_bytes: int = 1 << 20):
    """Scan compiled HLO text for explicitly replicated float tensors larger
    than min_bytes. Returns a list of (bytes, line) tuples.

    Complements capture_spmd_warnings: the warning catches the resharding
    fallback; this catches ops that were *assigned* a replicated sharding for
    activation-sized tensors.
    """
    itemsize = {"f32": 4, "bf16": 2, "f16": 2}
    out = []
    for line in hlo_text.splitlines():
        if not _REPLICATED_RE.search(line):
            continue
        m = _SHAPE_RE.search(line)
        if not m:
            continue
        dtype, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            n *= int(d)
        nbytes = n * itemsize[dtype]
        if nbytes >= min_bytes:
            out.append((nbytes, line.strip()[:200]))
    return out
