"""Rank-aware logging. Reference: ``deepspeed/utils/logging.py`` (logger, log_dist)."""

import logging
import os
import sys

_LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name: str = "deepspeed_tpu", level=logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        lg.addHandler(handler)
    return lg


logger = _create_logger(
    level=_LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), logging.INFO))


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks=None, level=logging.INFO) -> None:
    """Log only on the given process ranks (None or [-1] = all).

    Reference: ``deepspeed/utils/logging.py`` ``log_dist``.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str, _seen=set()) -> None:
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
