"""Device/host memory reporting.

Reference: ``deepspeed/runtime/utils.py:768`` (``see_memory_usage``) — reads the
CUDA caching-allocator stats. The TPU equivalent reads per-device memory stats
from the JAX runtime (``device.memory_stats()``) plus host RSS from /proc.
"""

from typing import Dict, Optional

from deepspeed_tpu.utils.logging import logger


def _host_mem_gb() -> Dict[str, float]:
    try:
        with open("/proc/self/status") as f:
            status = f.read()
        out = {}
        for key, label in (("VmRSS", "rss"), ("VmHWM", "rss_peak")):
            for line in status.splitlines():
                if line.startswith(key + ":"):
                    out[label] = float(line.split()[1]) / 1e6  # kB -> GB
        return out
    except Exception:
        return {}


def device_memory_stats(device=None) -> Dict[str, float]:
    """Bytes in use / limit for one device, in GB. Empty dict on platforms
    without memory_stats (CPU)."""
    import jax
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:
        stats = {}
    out = {}
    if "bytes_in_use" in stats:
        out["device_gb_in_use"] = stats["bytes_in_use"] / 1e9
    if "peak_bytes_in_use" in stats:
        out["device_gb_peak"] = stats["peak_bytes_in_use"] / 1e9
    if "bytes_limit" in stats:
        out["device_gb_limit"] = stats["bytes_limit"] / 1e9
    return out


def see_memory_usage(message: str, force: bool = False, device=None) -> Optional[str]:
    if not force:
        return None
    parts = [f"{k}={v:.2f}" for k, v in device_memory_stats(device).items()]
    parts += [f"host_{k}_gb={v:.2f}" for k, v in _host_mem_gb().items()]
    msg = f"MEM {message} | " + ", ".join(parts)
    logger.info(msg)
    return msg
