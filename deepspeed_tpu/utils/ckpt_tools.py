"""Checkpoint inspect / mesh-validate CLI (``dstpu_ckpt``).

Reference counterpart: ``deepspeed/checkpoint/`` (DeepSpeedCheckpoint,
reshape_3d_utils, universal_checkpoint) — ~1k LoC of shard surgery that
exists because the reference's checkpoints are rank-local torch files tied
to the TP/PP/DP degrees they were written with. Here checkpoints are Orbax
trees of GLOBAL arrays: loading at a different mesh is free (proved by
tests/unit/test_universal_checkpoint.py), so the tooling reduces to:

  inspect  — tags, step counters, config, param/optimizer tree summary
  validate — would the state restore onto mesh axes A x B x ...?  (every
             sharded dim must divide by the product of its mesh axes)

``reshape`` therefore does not exist: save-at-A/load-at-B needs no offline
rewrite. ``validate`` answers the question reshape existed to solve.
"""

import argparse
import json
import os
import sys
from typing import Optional

LATEST_FILE = "latest"


def _tags(ckpt_dir: str):
    tags = []
    for name in sorted(os.listdir(ckpt_dir)):
        sub = os.path.join(ckpt_dir, name)
        if not os.path.isdir(sub):
            continue
        if os.path.isfile(os.path.join(sub, "meta.json")) or \
                os.path.isdir(os.path.join(sub, "state")) or \
                any(n.startswith("state-v") for n in os.listdir(sub)):
            tags.append(name)
    return tags


def _resolve_tag(ckpt_dir: str, tag: Optional[str]) -> str:
    if tag is not None:
        return tag
    latest = os.path.join(ckpt_dir, LATEST_FILE)
    if os.path.exists(latest):
        with open(latest) as f:
            return f.read().strip()
    tags = _tags(ckpt_dir)
    if not tags:
        raise FileNotFoundError(f"no checkpoint tags under {ckpt_dir}")
    return tags[-1]


def _state_metadata(ckpt_dir: str, tag: str):
    """Abstract (shape/dtype) tree of the saved state, no data read."""
    from deepspeed_tpu.runtime.checkpointing import _resolve_pointer
    import orbax.checkpoint as ocp
    path = _resolve_pointer(
        os.path.abspath(os.path.join(ckpt_dir, tag, "state")))
    md = ocp.StandardCheckpointer().metadata(path)
    # StepMetadata wraps the tree (orbax >= 0.6); unwrap to the pytree
    item = getattr(md, "item_metadata", md)
    return getattr(item, "tree", item)


def _leaves_with_paths(meta):
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(
        meta, is_leaf=lambda x: hasattr(x, "shape"))
    out = []
    for path, leaf in flat:
        if hasattr(leaf, "shape"):
            out.append(("/".join(str(getattr(p, "key", p)) for p in path),
                        tuple(leaf.shape), str(getattr(leaf, "dtype", "?"))))
    return out


def cmd_inspect(args) -> int:
    ckpt_dir = args.dir
    if not os.path.isdir(ckpt_dir):
        print(f"error: no such checkpoint dir: {ckpt_dir}")
        return 1
    try:
        tags = _tags(ckpt_dir)
    except OSError as e:
        print(f"error: {e}")
        return 1
    latest = None
    if os.path.exists(os.path.join(ckpt_dir, LATEST_FILE)):
        with open(os.path.join(ckpt_dir, LATEST_FILE)) as f:
            latest = f.read().strip()
    print(f"checkpoint dir: {ckpt_dir}")
    print(f"tags: {', '.join(tags) or '(none)'}"
          + (f"   latest -> {latest}" if latest else ""))
    try:
        tag = _resolve_tag(ckpt_dir, args.tag)
    except FileNotFoundError as e:
        print(f"error: {e}")
        return 1
    meta_path = os.path.join(ckpt_dir, tag, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        cs = meta.get("client_state", {})
        cfg = meta.get("config", {})
        print(f"tag {tag}: global_steps={cs.get('global_steps')} "
              f"world_size={meta.get('world_size')}")
        zero = (cfg.get("zero_optimization") or {}).get("stage")
        mesh = (cfg.get("mesh") or {}).get("axes")
        print(f"  zero stage: {zero}   mesh axes: {mesh}")
    # infinity layout?
    inf = os.path.join(ckpt_dir, tag, "infinity_shapes.json")
    if os.path.exists(inf):
        with open(inf) as f:
            m = json.load(f)
        print(f"  infinity chunks: {m['num_layers']} layers x "
              f"chunk {m['chunk']} elems")
        return 0
    try:
        md = _state_metadata(ckpt_dir, tag)
    except Exception as e:  # noqa: BLE001 — metadata read is best-effort
        print(f"  (state metadata unavailable: {e})")
        return 0
    leaves = _leaves_with_paths(md)
    n_param = sum(int(__import__('numpy').prod(s)) for p, s, d in leaves
                  if p.startswith("params/"))
    n_total = sum(int(__import__('numpy').prod(s)) for _, s, _ in leaves)
    print(f"  state: {len(leaves)} arrays, params {n_param / 1e6:.2f}M, "
          f"total {n_total / 1e6:.2f}M elems")
    if args.verbose:
        for p, s, d in leaves:
            print(f"    {p}  {list(s)}  {d}")
    return 0


def _parse_mesh(spec: str):
    axes = {}
    for part in spec.split(","):
        k, v = part.split("=")
        axes[k.strip()] = int(v)
    return axes


def cmd_validate(args) -> int:
    """Check the saved state restores onto the target mesh: rebuild the
    sharding specs the engine would use and test divisibility per dim."""
    from deepspeed_tpu.parallel.mesh import AXIS_ORDER
    try:
        tag = _resolve_tag(args.dir, args.tag)
    except (FileNotFoundError, NotADirectoryError) as e:
        print(f"error: {e}")
        return 1
    axes = _parse_mesh(args.mesh)
    bad_axes = set(axes) - set(AXIS_ORDER)
    if bad_axes:
        print(f"unknown mesh axes: {sorted(bad_axes)} (valid: {AXIS_ORDER})")
        return 1
    try:
        md = _state_metadata(args.dir, tag)
    except Exception as e:  # noqa: BLE001
        print(f"cannot read state metadata: {e}")
        return 1
    # we don't know each param's logical axes from the checkpoint alone;
    # conservatively require every dim of every array to be divisible by
    # each mesh axis it COULD shard over (tensor / fsdp / pipe)
    leaves = _leaves_with_paths(md)
    problems = []
    check_sizes = [n for ax, n in axes.items()
                   if ax in ("tensor", "fsdp", "pipe") and n > 1]
    for path, shape, _ in leaves:
        if not path.startswith("params/"):
            continue
        for n in check_sizes:
            if not any(d % n == 0 for d in shape if d > 1):
                problems.append((path, shape, n))
    if problems:
        print(f"NOT restorable onto mesh {axes}: "
              f"{len(problems)} arrays have no dim divisible by the axis "
              "size:")
        for path, shape, n in problems[:10]:
            print(f"  {path} {list(shape)} vs axis size {n}")
        return 1
    print(f"OK: tag {tag} restores onto mesh {axes} "
          f"({len(leaves)} arrays checked)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="dstpu_ckpt",
        description="Inspect / mesh-validate deepspeed_tpu checkpoints")
    sub = p.add_subparsers(dest="cmd", required=True)
    pi = sub.add_parser("inspect", help="show tags, config, state summary")
    pi.add_argument("dir")
    pi.add_argument("--tag", default=None)
    pi.add_argument("-v", "--verbose", action="store_true")
    pi.set_defaults(fn=cmd_inspect)
    pv = sub.add_parser("validate",
                        help="check restorability onto a target mesh")
    pv.add_argument("dir")
    pv.add_argument("--tag", default=None)
    pv.add_argument("--mesh", required=True,
                    help="e.g. fsdp=2,tensor=4")
    pv.set_defaults(fn=cmd_validate)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
