"""Experiment monitoring: TensorBoard / W&B / CSV fan-out.

Reference: ``deepspeed/monitor/monitor.py:26`` (MonitorMaster) and the
per-sink writers (``monitor/{tensorboard,wandb,csv_monitor}.py``). Same event
contract: ``write_events([(name, value, step), ...])``. Only the process-0
host writes (reference gates on rank 0).
"""

import csv
import os
import time
from typing import List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    enabled = False

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass


def _is_rank0() -> bool:
    try:
        import jax
        return jax.process_index() == 0
    except Exception:
        return True


class TensorBoardMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        if not (cfg.enabled and _is_rank0()):
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception:
            try:
                from tensorboardX import SummaryWriter  # type: ignore
            except Exception:
                logger.warning("tensorboard requested but no SummaryWriter available")
                return
        out = os.path.join(cfg.output_path or "runs", cfg.job_name)
        self.writer = SummaryWriter(log_dir=out)
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), int(step))

    def flush(self):
        if self.enabled:
            self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        if not (cfg.enabled and _is_rank0()):
            return
        try:
            import wandb
        except Exception:
            logger.warning("wandb requested but not installed")
            return
        self.wandb = wandb
        wandb.init(project=cfg.project, group=cfg.group, entity=cfg.team,
                   name=cfg.job_name or None)
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.wandb.log({name: float(value)}, step=int(step))


class CSVMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        if not (cfg.enabled and _is_rank0()):
            return
        self.dir = os.path.join(cfg.output_path or "csv_logs", cfg.job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            fname = os.path.join(self.dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name, "time"])
                w.writerow([int(step), float(value), time.time()])


class MonitorMaster(Monitor):
    """Fans one event stream out to every enabled sink (reference:
    monitor.py:26)."""

    def __init__(self, config):
        self.sinks = [
            TensorBoardMonitor(config.tensorboard),
            WandbMonitor(config.wandb),
            CSVMonitor(config.csv_monitor),
        ]
        self.enabled = any(s.enabled for s in self.sinks)

    def write_events(self, events: List[Event]) -> None:
        for s in self.sinks:
            if s.enabled:
                s.write_events(events)

    def flush(self):
        for s in self.sinks:
            if s.enabled:
                s.flush()
