"""Experiment monitoring: TensorBoard / W&B / CSV / JSONL fan-out.

Reference: ``deepspeed/monitor/monitor.py:26`` (MonitorMaster) and the
per-sink writers (``monitor/{tensorboard,wandb,csv_monitor}.py``). Same event
contract: ``write_events([(name, value, step), ...])``. Only the process-0
host writes (reference gates on rank 0).

PR-3 additions: structured records (``write_records([{...}, ...])``) carry
telemetry windows and anomaly events — the JSONL sink writes them verbatim
(machine-readable, one JSON object per line); scalar sinks receive a scalar
projection (``anomaly/<rule>`` = severity code). The CSV sink caches open
file handles (one open per metric per run, not per event) and the W&B sink
batches one ``wandb.log`` call per step.
"""

import csv
import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Tuple

# ONE severity->code mapping (telemetry/anomaly.py owns it); a drifting
# duplicate here would make write_records' scalar projection disagree with
# the anomaly/* events the engine emits directly for the same record
from deepspeed_tpu.telemetry.anomaly import SEVERITY_NUM as _SEVERITY_NUM
from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


def _jsonable(value):
    """Strict-JSON projection: NaN/Infinity have no JSON spelling and would
    make the machine-readable sink unparseable exactly when a run diverges —
    map them to null (recursively)."""
    if isinstance(value, float) and not math.isfinite(value):
        return None
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return value


def _record_to_event(record: Dict[str, Any]) -> Optional[Event]:
    """Scalar projection of a structured record for sinks that only plot
    numbers: anomaly records become ``anomaly/<rule>`` = severity code;
    records carrying an explicit name/value pass through; the rest (e.g.
    full telemetry windows, already emitted as telemetry/* scalars) drop."""
    step = int(record.get("step", 0) or 0)
    if record.get("type") == "anomaly":
        return (f"anomaly/{record.get('rule', 'unknown')}",
                float(_SEVERITY_NUM.get(record.get("severity"), 1)), step)
    if "name" in record and "value" in record:
        try:
            return (str(record["name"]), float(record["value"]), step)
        except (TypeError, ValueError):
            return None
    return None


class Monitor:
    enabled = False

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def write_records(self, records: List[Dict[str, Any]]) -> None:
        """Structured records; default implementation projects to scalar
        events (JSONL overrides to keep the full structure)."""
        events = [e for e in map(_record_to_event, records) if e is not None]
        if events:
            self.write_events(events)

    def flush(self) -> None:
        pass


def _is_rank0() -> bool:
    try:
        import jax
        return jax.process_index() == 0
    except Exception:
        return True


class TensorBoardMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        if not (cfg.enabled and _is_rank0()):
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception:
            try:
                from tensorboardX import SummaryWriter  # type: ignore
            except Exception:
                logger.warning("tensorboard requested but no SummaryWriter available")
                return
        out = os.path.join(cfg.output_path or "runs", cfg.job_name)
        self.writer = SummaryWriter(log_dir=out)
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, float(value), int(step))

    def flush(self):
        if self.enabled:
            self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        if not (cfg.enabled and _is_rank0()):
            return
        try:
            import wandb
        except Exception:
            logger.warning("wandb requested but not installed")
            return
        self.wandb = wandb
        wandb.init(project=cfg.project, group=cfg.group, entity=cfg.team,
                   name=cfg.job_name or None)
        self.enabled = True

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        # one network call per STEP, not per event (the engine hands the
        # whole boundary batch in one write_events)
        by_step: Dict[int, Dict[str, float]] = {}
        for name, value, step in events:
            by_step.setdefault(int(step), {})[name] = float(value)
        for step in sorted(by_step):
            self.wandb.log(by_step[step], step=step)


class CSVMonitor(Monitor):
    def __init__(self, cfg):
        self.enabled = False
        # open handles cached per metric: one open/close per run, not per
        # event (flush() closes them; the next write reopens in append
        # mode). Initialized BEFORE the enabled gate: flush()/__del__ on a
        # disabled instance must not AttributeError
        self._files: Dict[str, Tuple[Any, Any]] = {}
        if not (cfg.enabled and _is_rank0()):
            return
        self.dir = os.path.join(cfg.output_path or "csv_logs", cfg.job_name)
        os.makedirs(self.dir, exist_ok=True)
        self.enabled = True

    def _writer(self, name: str):
        ent = self._files.get(name)
        if ent is None:
            fname = os.path.join(self.dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            f = open(fname, "a", newline="")
            w = csv.writer(f)
            if new:
                w.writerow(["step", name, "time"])
            self._files[name] = ent = (f, w)
        return ent

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        for name, value, step in events:
            _, w = self._writer(name)
            w.writerow([int(step), float(value), time.time()])
        for f, _ in self._files.values():
            # one cheap flush per boundary batch: rows are durable without
            # the old per-event open/close (a crash must not eat the window
            # that explains it)
            f.flush()

    def flush(self):
        for f, _ in self._files.values():
            try:
                f.flush()
                f.close()
            except Exception:  # noqa: BLE001 - a dead handle must not stop flush
                pass
        self._files = {}

    def __del__(self):  # best-effort durability on interpreter exit
        try:
            self.flush()
        except Exception:  # noqa: BLE001
            pass


class JSONLMonitor(Monitor):
    """Machine-readable sink: one JSON object per line. Scalar events are
    written as ``{"type": "scalar", "name", "value", "step", "time"}``;
    structured records (telemetry windows, anomaly events) verbatim plus a
    timestamp — the format downstream alerting actually wants to tail."""

    def __init__(self, path: str):
        self.enabled = False
        self.path = path
        self._f = None
        if not (path and _is_rank0()):
            return
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.enabled = True

    def _handle(self):
        if self._f is None:
            self._f = open(self.path, "a")
        return self._f

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled:
            return
        now = time.time()
        f = self._handle()
        for name, value, step in events:
            f.write(json.dumps({"type": "scalar", "name": name,
                                "value": _jsonable(float(value)),
                                "step": int(step), "time": now}) + "\n")
        f.flush()  # durable per boundary batch, not per interpreter exit

    def write_records(self, records: List[Dict[str, Any]]) -> None:
        if not self.enabled:
            return
        now = time.time()
        f = self._handle()
        for r in records:
            rec = _jsonable(dict(r))
            rec.setdefault("time", now)
            f.write(json.dumps(rec, default=str) + "\n")
        f.flush()

    def flush(self):
        if self._f is not None:
            try:
                self._f.flush()
                self._f.close()
            except Exception:  # noqa: BLE001
                pass
            self._f = None

    def __del__(self):
        try:
            self.flush()
        except Exception:  # noqa: BLE001
            pass


class MonitorMaster(Monitor):
    """Fans one event stream out to every enabled sink (reference:
    monitor.py:26)."""

    def __init__(self, config):
        self.sinks = [
            TensorBoardMonitor(config.tensorboard),
            WandbMonitor(config.wandb),
            CSVMonitor(config.csv_monitor),
        ]
        jsonl_path = None
        jm = getattr(config, "json_monitor", None)
        if jm is not None and jm.enabled:
            jsonl_path = os.path.join(jm.output_path or "jsonl_logs",
                                      (jm.job_name or "job") + ".jsonl")
        else:
            tel = getattr(config, "telemetry", None)
            # telemetry.enabled is the documented master switch — jsonl_path
            # alone must not activate the sink (use the json_monitor section
            # for a standalone JSONL sink)
            if tel is not None and getattr(tel, "enabled", False) \
                    and getattr(tel, "jsonl_path", None):
                jsonl_path = tel.jsonl_path
        if jsonl_path:
            self.sinks.append(JSONLMonitor(jsonl_path))
        self.enabled = any(s.enabled for s in self.sinks)

    def write_events(self, events: List[Event]) -> None:
        for s in self.sinks:
            if s.enabled:
                s.write_events(events)

    def write_records(self, records: List[Dict[str, Any]]) -> None:
        for s in self.sinks:
            if s.enabled:
                s.write_records(records)

    def flush(self):
        for s in self.sinks:
            if s.enabled:
                s.flush()
