from deepspeed_tpu.monitor.monitor import (MonitorMaster, TensorBoardMonitor,
                                           WandbMonitor, CSVMonitor,
                                           JSONLMonitor)
