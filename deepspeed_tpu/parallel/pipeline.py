"""Pipeline parallelism — compiled schedule over the `pipe` mesh axis.

Reference: ``runtime/pipe/module.py:82`` (PipelineModule layer partitioning),
``runtime/pipe/schedule.py`` (instruction streams: TrainSchedule 1F1B),
``runtime/pipe/engine.py:37`` (interpreter executing Send/Recv/Forward/
Backward instructions over torch.distributed p2p), ``runtime/pipe/p2p.py``.

TPU-native re-design: the reference interprets a per-rank instruction list in
Python, issuing eager p2p ops. Here the ENTIRE pipeline schedule is one XLA
program: a `lax.scan` over (num_microbatches + stages - 1) ticks inside a
`jax.shard_map` over the `pipe` axis, with `lax.ppermute` rotating
activations stage->stage over ICI. XLA overlaps the permute with the next
tick's compute (the Send/Recv instruction taxonomy disappears; the schedule
is data flow). The backward schedule is jax.grad of the scan — autodiff
reverses the ppermutes, which IS the reverse pipeline.

Layer placement: models stack per-layer params on a leading `layers` dim
(models/transformer.py scan design), so "partition by layers" is just
sharding that dim over `pipe` — the equivalent of PipelineModule's
`_partition_layers` with the `uniform` policy. Parameter-balanced placement
is a sharding choice, not a code structure.

The microbatch loop doubles as gradient accumulation: engine maps
`gradient_accumulation_steps` to `num_microbatches` (same as the reference's
PipelineEngine.train_batch contract).
"""

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger


def pipeline_spmd(stage_fn: Callable, mesh: Mesh, *, num_microbatches: int,
                  pipe_axis: str = "pipe", remat_stage: bool = True):
    """Build fn(stage_params, x_microbatches) -> y_microbatches running the
    GPipe-style rotation compiled into one program.

    stage_fn(stage_params, x) applies this stage's layer stack to one
    microbatch activation x [mb, S, H]. stage_params leaves have a leading
    local-layers dim (global layers sharded over pipe).
    x_microbatches: [M, mb, S, H] (replicated over pipe; only stage 0 reads).
    Returns y_microbatches [M, mb, S, H] broadcast to all stages.
    """
    n_stages = mesh.shape[pipe_axis]
    M = num_microbatches
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)

    def pipelined(stage_params, x_mb):
        # manual over pipe; all other axes stay under GSPMD (auto)
        sidx = lax.axis_index(pipe_axis)
        is_first = sidx == 0
        is_last = sidx == n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        mb_shape = x_mb.shape[1:]
        ticks = M + n_stages - 1

        def tick(carry, t):
            recv, outputs = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            first_in = lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0,
                                                keepdims=False)
            inp = jnp.where(is_first, first_in, recv)
            y = stage_fn(stage_params, inp)
            # collect on the last stage: tick t finishes microbatch t-(P-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            valid = jnp.logical_and(is_last, t >= n_stages - 1)
            prev = lax.dynamic_index_in_dim(outputs, out_idx, axis=0,
                                            keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, prev), out_idx, axis=0)
            new_recv = lax.ppermute(y, pipe_axis, perm) if n_stages > 1 else y
            return (new_recv, outputs), None

        outputs0 = jnp.zeros((M,) + mb_shape, x_mb.dtype)
        recv0 = jnp.zeros(mb_shape, x_mb.dtype)
        (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(ticks))
        # broadcast final outputs from the last stage to all pipe ranks so
        # the (replicated-over-pipe) head/loss sees real data everywhere
        outputs = lax.psum(jnp.where(is_last, outputs, 0.0), pipe_axis)
        return outputs

    # stage_params: stacked layer dim sharded over pipe (pytree-prefix spec);
    # x replicated over pipe. Axes not named stay under GSPMD (auto).
    wrapped = jax.shard_map(pipelined, mesh=mesh,
                            in_specs=(P(pipe_axis), P()),
                            out_specs=P(),
                            axis_names={pipe_axis},
                            check_vma=False)
    return wrapped


def bubble_fraction(num_microbatches: int, stages: int) -> float:
    """Pipeline bubble overhead of the compiled schedule (same as GPipe/1F1B
    forward bubble: (P-1)/(M+P-1))."""
    return (stages - 1) / (num_microbatches + stages - 1)
