"""Pipeline parallelism — compiled schedule over the `pipe` mesh axis.

Reference: ``runtime/pipe/module.py:82`` (PipelineModule layer partitioning),
``runtime/pipe/schedule.py`` (instruction streams: TrainSchedule 1F1B),
``runtime/pipe/engine.py:37`` (interpreter executing Send/Recv/Forward/
Backward instructions over torch.distributed p2p), ``runtime/pipe/p2p.py``.

TPU-native re-design: the reference interprets a per-rank instruction list in
Python, issuing eager p2p ops. Here the ENTIRE pipeline schedule is one XLA
program: a `lax.scan` over (num_microbatches + stages - 1) ticks inside a
`jax.shard_map` over the `pipe` axis, with `lax.ppermute` rotating
activations stage->stage over ICI. XLA overlaps the permute with the next
tick's compute (the Send/Recv instruction taxonomy disappears; the schedule
is data flow). The backward schedule is jax.grad of the scan — autodiff
reverses the ppermutes, which IS the reverse pipeline.

Layer placement: models stack per-layer params on a leading `layers` dim
(models/transformer.py scan design), so "partition by layers" is just
sharding that dim over `pipe` — the equivalent of PipelineModule's
`_partition_layers` with the `uniform` policy. Parameter-balanced placement
is a sharding choice, not a code structure.

The microbatch loop doubles as gradient accumulation: engine maps
`gradient_accumulation_steps` to `num_microbatches` (same as the reference's
PipelineEngine.train_batch contract).
"""

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger


def pipeline_spmd(stage_fn: Callable, mesh: Mesh, *, num_microbatches: int,
                  pipe_axis: str = "pipe", remat_stage: bool = True):
    """Build fn(stage_params, x_microbatches) -> y_microbatches running the
    GPipe-style rotation compiled into one program.

    stage_fn(stage_params, x) applies this stage's layer stack to one
    microbatch activation x [mb, S, H]. stage_params leaves have a leading
    local-layers dim (global layers sharded over pipe).
    x_microbatches: [M, mb, S, H] (replicated over pipe; only stage 0 reads).
    Returns y_microbatches [M, mb, S, H] broadcast to all stages.
    """
    n_stages = mesh.shape[pipe_axis]
    M = num_microbatches
    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)

    def pipelined(stage_params, x_mb):
        # manual over pipe; all other axes stay under GSPMD (auto)
        sidx = lax.axis_index(pipe_axis)
        is_first = sidx == 0
        is_last = sidx == n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        mb_shape = x_mb.shape[1:]
        ticks = M + n_stages - 1

        def tick(carry, t):
            recv, outputs = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            first_in = lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0,
                                                keepdims=False)
            inp = jnp.where(is_first, first_in, recv)
            y = stage_fn(stage_params, inp)
            # collect on the last stage: tick t finishes microbatch t-(P-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            valid = jnp.logical_and(is_last, t >= n_stages - 1)
            prev = lax.dynamic_index_in_dim(outputs, out_idx, axis=0,
                                            keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, prev), out_idx, axis=0)
            new_recv = lax.ppermute(y, pipe_axis, perm) if n_stages > 1 else y
            return (new_recv, outputs), None

        outputs0 = jnp.zeros((M,) + mb_shape, x_mb.dtype)
        recv0 = jnp.zeros(mb_shape, x_mb.dtype)
        (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(ticks))
        # broadcast final outputs from the last stage to all pipe ranks so
        # the (replicated-over-pipe) head/loss sees real data everywhere
        outputs = lax.psum(jnp.where(is_last, outputs, 0.0), pipe_axis)
        return outputs

    # stage_params: stacked layer dim sharded over pipe (pytree-prefix spec);
    # x replicated over pipe. Axes not named stay under GSPMD (auto).
    wrapped = jax.shard_map(pipelined, mesh=mesh,
                            in_specs=(P(pipe_axis), P()),
                            out_specs=P(),
                            axis_names={pipe_axis},
                            check_vma=False)
    return wrapped


def bubble_fraction(num_microbatches: int, stages: int) -> float:
    """Pipeline bubble overhead of the compiled schedule (same as GPipe/1F1B
    forward bubble: (P-1)/(M+P-1))."""
    return (stages - 1) / (num_microbatches + stages - 1)


# --------------------------------------------------------------------------
# 1F1B training schedule
# --------------------------------------------------------------------------

def _tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def _tree_add(a, b):
    return jax.tree.map(jnp.add, a, b)


def make_pipeline_1f1b(embed_fn: Callable, stage_fn: Callable,
                       head_loss_fn: Callable, mesh: Mesh, *,
                       num_microbatches: int, aux_weight: float = 0.0,
                       pipe_axis: str = "pipe"):
    """Compiled 1F1B schedule producing loss AND grads in one interleaved
    tick loop.

    Reference: ``runtime/pipe/schedule.py:186`` (TrainSchedule — the 1F1B
    instruction stream that bounds live activations to ~stages instead of
    ~microbatches) executed by ``runtime/pipe/engine.py:37``.

    TPU-native re-design: the reference interprets per-rank instruction lists
    in Python with eager p2p. Here one `lax.scan` over M + 2(P-1) ticks runs
    inside `shard_map` over the pipe axis; each tick every stage does (at
    most) one microbatch FORWARD and one microbatch BACKWARD — the backward
    via a local `jax.vjp` of the stage (which recomputes the stage forward:
    remat by construction), with `lax.ppermute` carrying activations down
    and cotangents up the pipe. Stage inputs wait in a ring buffer of 2P
    slots, so peak live activations are O(P) microbatches vs O(M) for the
    all-forward-then-backward autodiff schedule. The loss head runs under a
    `lax.cond` on the last stage only (TPU control flow is per-core; the
    branches contain no collectives, so non-uniform predicates are legal and
    the head matmul is NOT wasted on every stage).

    Schedule (stage s, microbatch i, P stages):
        forward  tick  f(s, i) = s + i
        backward tick  b(s, i) = 2(P-1) - s + i
    — the last stage backpropagates a microbatch in the same tick it
    forwards it, earlier stages 2 ticks later per hop; in steady state each
    tick is exactly one F and one B (hence the name).

    Contracts (all collective-free so they can sit under `lax.cond`):
        embed_fn(other_params, tokens[mb,S]) -> x [mb,S,H]
        stage_fn(stage_params, x, mb_idx, mask, rng) -> (y [mb,S,H], aux)
        head_loss_fn(other_params, y, labels[mb,S]) -> scalar mean loss
    Returns loss_and_grads(stage_params, other_params, tokens [M,mb,S],
    labels [M,mb,S], mask [M,mb,S]|None, rng) -> (loss, dstage, dother);
    wrap with `as_loss_fn` for a jax.grad-compatible scalar loss.
    """
    n_stages = mesh.shape[pipe_axis]
    M = num_microbatches
    Pn = n_stages
    R = 2 * Pn
    T = M + 2 * (Pn - 1)
    fwd_perm = [(i, i + 1) for i in range(Pn - 1)]
    bwd_perm = [(i + 1, i) for i in range(Pn - 1)]

    def body(stage_params, other_params, tokens, labels, mask, rng):
        s = lax.axis_index(pipe_axis)
        is_first = s == 0
        is_last = s == Pn - 1

        x0 = embed_fn(other_params, tokens[0])  # shape/dtype probe (cheap)
        mb_shape, mb_dtype = x0.shape, x0.dtype
        zeros_other = _tree_zeros_like(other_params)

        def run_stage(sp, x, mb_idx):
            return stage_fn(sp, x, mb_idx,
                            None if mask is None else mask[mb_idx], rng)

        def tick(carry, t):
            fwd_recv, bwd_recv, ring, dstage, dother, loss_sum = carry

            # ---------------- forward subtick ----------------
            f_i = t - s
            f_valid = jnp.logical_and(f_i >= 0, f_i < M)
            f_ic = jnp.clip(f_i, 0, M - 1)
            tok_f = lax.dynamic_index_in_dim(tokens, f_ic, 0, keepdims=False)
            lab_f = lax.dynamic_index_in_dim(labels, f_ic, 0, keepdims=False)
            # real branch: the gather only runs on stage 0 (collective-free)
            x_in = lax.cond(is_first,
                            lambda r: embed_fn(other_params, tok_f).astype(
                                mb_dtype),
                            lambda r: r, fwd_recv)

            y, aux = lax.cond(
                f_valid,
                lambda x: run_stage(stage_params, x, f_ic),
                lambda x: (jnp.zeros(mb_shape, mb_dtype), jnp.float32(0.0)),
                x_in)
            loss_sum = loss_sum + jnp.where(f_valid,
                                            (aux_weight / M) * aux, 0.0)

            slot = jnp.mod(f_ic, R)
            old = lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False)
            ring = lax.dynamic_update_index_in_dim(
                ring, jnp.where(f_valid, x_in, old), slot, 0)

            # loss head + backward seed — last stage only (real branch:
            # collective-free, so neither the head matmul nor the grad
            # accumulation into dother runs on the other P-1 stages)
            def head_branch(ops):
                yy, lab, acc = ops
                loss_mb, pull = jax.vjp(
                    lambda op, a: head_loss_fn(op, a, lab), other_params, yy)
                dop, dy = pull(jnp.float32(1.0 / M))
                return loss_mb / M, _tree_add(acc, dop), dy

            def head_zero(ops):
                yy, _, acc = ops
                return jnp.float32(0.0), acc, jnp.zeros_like(yy)

            loss_mb, dother, dy = lax.cond(
                jnp.logical_and(is_last, f_valid), head_branch, head_zero,
                (y, lab_f, dother))
            loss_sum = loss_sum + loss_mb

            # ---------------- backward subtick ----------------
            b_i = t - 2 * (Pn - 1) + s
            b_valid = jnp.logical_and(b_i >= 0, b_i < M)
            b_ic = jnp.clip(b_i, 0, M - 1)
            x_b = lax.dynamic_index_in_dim(ring, jnp.mod(b_ic, R), 0,
                                           keepdims=False)
            g_in = jnp.where(is_last, dy, bwd_recv)
            tok_b = lax.dynamic_index_in_dim(tokens, b_ic, 0, keepdims=False)

            def b_branch(ops):
                xb, g, acc = ops
                _, pull = jax.vjp(
                    lambda sp, xx: run_stage(sp, xx, b_ic), stage_params, xb)
                dsp, dx = pull((g, jnp.float32(aux_weight / M)))
                return _tree_add(acc, dsp), dx

            def b_zero(ops):
                xb, _, acc = ops
                return acc, jnp.zeros_like(xb)

            dstage, dx = lax.cond(b_valid, b_branch, b_zero,
                                  (x_b, g_in, dstage))

            # embedding backward — first stage only (recomputes the gather;
            # the accumulation also only runs there)
            def e_branch(ops):
                d, acc = ops
                _, pull = jax.vjp(lambda op: embed_fn(op, tok_b), other_params)
                return _tree_add(acc, pull(d)[0])

            dother = lax.cond(
                jnp.logical_and(b_valid, is_first), e_branch,
                lambda ops: ops[1], (dx, dother))

            # ---------------- communication (outside all conds) -----------
            if Pn > 1:
                fwd_recv = lax.ppermute(y, pipe_axis, fwd_perm)
                bwd_recv = lax.ppermute(dx, pipe_axis, bwd_perm)
            else:
                fwd_recv, bwd_recv = y, dx
            return (fwd_recv, bwd_recv, ring, dstage, dother, loss_sum), None

        carry0 = (jnp.zeros(mb_shape, mb_dtype),
                  jnp.zeros(mb_shape, mb_dtype),
                  jnp.zeros((R,) + mb_shape, mb_dtype),
                  _tree_zeros_like(stage_params),
                  zeros_other,
                  jnp.float32(0.0))
        (_, _, _, dstage, dother, loss_sum), _ = lax.scan(
            tick, carry0, jnp.arange(T))

        loss = lax.psum(loss_sum, pipe_axis)
        # reduce in f32: better accumulation numerics for bf16 grads, and it
        # sidesteps an XLA-CPU AllReducePromotion crash on bf16 all-reduce
        dother = jax.tree.map(
            lambda a: lax.psum(a.astype(jnp.float32), pipe_axis).astype(
                a.dtype), dother)
        return loss, dstage, dother

    mask_spec = P()
    wrapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(pipe_axis), P(), P(), P(), mask_spec, P()),
        out_specs=(P(), P(pipe_axis), P()),
        axis_names={pipe_axis},
        check_vma=False)
    return wrapped


def as_loss_fn(pipeline_fn):
    """Wrap make_pipeline_1f1b's output as scalar-loss fn for jax.grad: the
    grads computed inside the schedule become the custom-vjp cotangents."""
    import numpy as np

    def _zero_ct(x):
        return jax.tree.map(
            lambda a: np.zeros(a.shape, jax.dtypes.float0)
            if not jnp.issubdtype(a.dtype, jnp.floating)
            else jnp.zeros_like(a), x)

    @jax.custom_vjp
    def ploss(stage_params, other_params, tokens, labels, mask, rng):
        loss, _, _ = pipeline_fn(stage_params, other_params, tokens, labels,
                                 mask, rng)
        return loss

    def fwd(stage_params, other_params, tokens, labels, mask, rng):
        loss, dsp, dop = pipeline_fn(stage_params, other_params, tokens,
                                     labels, mask, rng)
        return loss, (dsp, dop, tokens, labels, mask, rng)

    def bwd(res, g):
        dsp, dop, tokens, labels, mask, rng = res
        scale = lambda t: jax.tree.map(  # noqa: E731
            lambda a: (a.astype(jnp.float32) * g).astype(a.dtype), t)
        return (scale(dsp), scale(dop), _zero_ct(tokens), _zero_ct(labels),
                _zero_ct(mask), _zero_ct(rng))

    ploss.defvjp(fwd, bwd)
    return ploss
