from deepspeed_tpu.parallel.mesh import (
    AXIS_ORDER, MeshPlan, Topology, build_mesh, plan_from_config,
    single_device_mesh,
)
from deepspeed_tpu.parallel.partitioning import (
    ShardingRules, make_rules, logical_to_sharding, spec_tree, shard_params,
    num_params, params_bytes,
)
