"""Logical-axis partitioning: map parameter logical axes -> mesh axes.

This replaces the reference's partitioned-tensor bookkeeping (`ds_tensor`,
`ds_id`, partition/allgather primitives — ``runtime/zero/partition_parameters.py``)
with declarative sharding: every parameter carries a tuple of *logical* axis
names (e.g. ("embed", "mlp")), and a rules table maps logical names to mesh
axis names. GSPMD then inserts the all-gathers/reduce-scatters the reference
implements by hand.

t5x/flax use the same idea; the implementation here is our own and tuned to the
ZeRO-stage semantics described in zero/config.
"""

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxis = Union[str, Tuple[str, ...], None]


@dataclasses.dataclass
class ShardingRules:
    """Ordered logical->mesh rules; first match wins (like t5x rule lists)."""
    rules: Tuple[Tuple[str, MeshAxis], ...]

    def mesh_axes(self, logical_axes: Optional[Tuple[Optional[str], ...]]):
        if logical_axes is None:
            return P()
        table = dict(self.rules)
        out = []
        used = set()
        for name in logical_axes:
            axis = table.get(name) if name is not None else None
            # one mesh axis can only be used once per spec
            key = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
            if axis is not None and any(a in used for a in key):
                axis = None
            if axis is not None:
                used.update(key)
            out.append(tuple(axis) if isinstance(axis, list) else axis)
        while out and out[-1] is None:
            out.pop()
        return P(*out)


# Default logical-axis vocabulary used by deepspeed_tpu.models:
#   "embed"    — model hidden dim
#   "vocab"    — vocabulary dim
#   "mlp"      — MLP intermediate dim
#   "heads"    — attention heads dim
#   "kv"       — per-head dim
#   "qkv"      — fused qkv output dim
#   "expert"   — expert index dim (MoE stacked experts)
#   "unmodeled"— small params (biases, norms)
#   "layers"   — scanned-layer stacking dim

def make_rules(zero_stage: int, tp: bool = True, pipe: bool = False,
               fsdp_axis: str = "fsdp", tensor_axis: str = "tensor") -> ShardingRules:
    """Build the rules table realizing a ZeRO stage + optional TP + PP.

    stage <= 2: params replicated across DP — logical axes map only to tensor.
    stage == 3: the largest logical dim additionally shards over `fsdp`
    (all-gather-on-use inserted by GSPMD = ZeRO-3 fetch/release).
    pipe: the stacked `layers` dim shards over `pipe` (= the reference's
    PipelineModule layer partitioning, as a sharding choice).
    """
    t = tensor_axis if tp else None
    layers_axis = "pipe" if pipe else None
    if zero_stage >= 3:
        rules = (
            ("vocab", (fsdp_axis, t) if t else fsdp_axis),
            ("embed", fsdp_axis),
            ("mlp", t if t else fsdp_axis),
            ("heads", t if t else fsdp_axis),
            ("qkv", t if t else fsdp_axis),
            ("kv", None),
            ("expert", "expert"),
            ("layers", layers_axis),
            ("unmodeled", None),
        )
    else:
        rules = (
            ("vocab", t),
            ("embed", None),
            ("mlp", t),
            ("heads", t),
            ("qkv", t),
            ("kv", None),
            ("expert", "expert"),
            ("layers", layers_axis),
            ("unmodeled", None),
        )
    return ShardingRules(rules=tuple((k, v) for k, v in rules))


# --------------------------------------------------------------------------
# Param metadata pytrees
# --------------------------------------------------------------------------

def logical_to_sharding(logical_tree, mesh: Mesh, rules: ShardingRules):
    """Map a pytree of logical-axis tuples to a pytree of NamedSharding."""
    def one(axes):
        return NamedSharding(mesh, rules.mesh_axes(axes))
    return jax.tree.map(one, logical_tree, is_leaf=lambda x: x is None or isinstance(x, tuple))


def spec_tree(logical_tree, rules: ShardingRules):
    def one(axes):
        return rules.mesh_axes(axes)
    return jax.tree.map(one, logical_tree, is_leaf=lambda x: x is None or isinstance(x, tuple))


def shard_params(params, shardings):
    return jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)


# --------------------------------------------------------------------------
# Chunked tensor-parallel collective-matmul overlap
# --------------------------------------------------------------------------

TENSOR_AXIS = "tensor"

_OVERLAP_WARNED = False


def _warn_overlap_unhosted(chunks):
    global _OVERLAP_WARNED
    if _OVERLAP_WARNED:
        return
    _OVERLAP_WARNED = True
    from deepspeed_tpu.utils.logging import logger
    logger.warning(
        f"tp_overlap_chunks={chunks}: a '{TENSOR_AXIS}' mesh axis is active "
        "but the trace context cannot host the chunked collective-matmul "
        "overlap (a surrounding manual shard_map region — e.g. "
        "comm.deferred_grad_sync — owns the partitioning); the row-parallel "
        "projections fall back to the serial matmul with an exposed "
        "boundary all-reduce")


def _tp_degree_for_overlap():
    """(mesh, active tensor-parallel degree) usable for the chunked
    decomposition — degree 0 when the current context cannot host it: no
    mesh, tensor absent or size 1, tensor already manual (nested shard_map
    regions own it), or any partially-manual region (the nested shard_map
    cannot be established from inside another manual region)."""
    from deepspeed_tpu.parallel.context import physical_mesh_env
    env_mesh, shape, bound = physical_mesh_env()
    if env_mesh is None:
        return None, 0
    tp = shape.get(TENSOR_AXIS, 1)
    if tp <= 1:
        return env_mesh, 0
    try:
        from jax.sharding import AxisType, get_abstract_mesh
        am = get_abstract_mesh()
        if am.axis_names and any(t is AxisType.Manual
                                 for t in getattr(am, "axis_types", ())):
            return env_mesh, 0
    except Exception:
        pass
    if TENSOR_AXIS in bound:
        return env_mesh, 0
    return env_mesh, tp


def row_parallel_matmul(x, w, *, chunks: int = 0):
    """``x @ w`` for a row-parallel weight (contraction dim sharded over the
    ``tensor`` mesh axis) with the tensor-axis reduction DECOMPOSED into
    ``chunks`` independent psums.

    GSPMD compiles the plain matmul to one local matmul + ONE all-reduce of
    the whole [B, S, H] output — a serial wire bubble at the end of every
    row-parallel projection. Chunking the rows makes chunk i's all-reduce
    and chunk i+1's matmul independent ops the latency-hiding scheduler can
    interleave (the collective-matmul overlap the reference gets from
    ``overlap_comm`` CUDA streams). Bit-identical to the unchunked path:
    each output element still sums the same per-shard partials in the same
    order — only the *grouping* of elements per collective changes. The
    BACKWARD is pinned to the plain matmul's own vjp via ``jax.custom_vjp``:
    auto-transposing the chunked region would split the weight-grad's
    sequence contraction per chunk (partial sums of partials — a genuine
    float reordering), whereas the plain vjp is the exact program the
    unchunked path compiles, so end-to-end training parity stays exact.

    Expressed as a partial-auto ``shard_map`` manual over ``tensor`` only
    (the deferred-grad-sync machinery, comm/schedule.py): batch axes stay
    auto, so GSPMD keeps partitioning the chunk matmuls over data/fsdp.
    Falls back to the plain matmul whenever the context can't host the
    decomposition (no tensor axis, nested manual region, indivisible
    shapes) — enabling the config on a 1-chip run changes nothing.
    """
    env_mesh, tp = _tp_degree_for_overlap()
    if not tp:
        if chunks and chunks > 1 and env_mesh is not None \
                and dict(env_mesh.shape).get(TENSOR_AXIS, 1) > 1:
            # a tensor axis EXISTS but the context can't host the overlap
            # (manual region owns it, e.g. comm.deferred_grad_sync's
            # shard_map) — say so once instead of silently serializing the
            # projection, the exact defect the serialized-backward corpus
            # entry plants
            _warn_overlap_unhosted(chunks)
        return x @ w
    if not chunks or chunks <= 1 or w.ndim != 2 \
            or x.shape[-1] != w.shape[0] or w.shape[0] % tp or x.ndim < 2:
        return x @ w
    # chunk along the second-to-last (sequence) dim; largest divisor <= chunks
    dim = x.ndim - 2
    c = min(int(chunks), int(x.shape[dim]))
    while c > 1 and x.shape[dim] % c:
        c -= 1
    if c <= 1:
        return x @ w
    from jax import lax as _lax
    from jax.sharding import PartitionSpec as _P
    from deepspeed_tpu.comm.schedule import shard_map_compat
    size = x.shape[dim] // c

    def body(xl, wl):
        parts = []
        for i in range(c):
            xc = _lax.slice_in_dim(xl, i * size, (i + 1) * size, axis=dim)
            parts.append(_lax.psum(
                jnp.matmul(xc, wl), TENSOR_AXIS))
        return jnp.concatenate(parts, axis=dim)

    in_x = _P(*([None] * (x.ndim - 1) + [TENSOR_AXIS]))

    @jax.custom_vjp
    def chunked(x, w):
        fn = shard_map_compat(body, env_mesh,
                              in_specs=(in_x, _P(TENSOR_AXIS, None)),
                              out_specs=_P(),
                              manual_axes=(TENSOR_AXIS,))
        return fn(x, w)

    def chunked_fwd(x, w):
        return chunked(x, w), (x, w)

    def chunked_bwd(res, g):
        xr, wr = res
        _, vjp = jax.vjp(lambda a, b: jnp.matmul(a, b), xr, wr)
        return vjp(g)

    chunked.defvjp(chunked_fwd, chunked_bwd)
    try:
        return chunked(x, w)
    except Exception as e:  # noqa: BLE001 — composition contexts we can't host
        # loud fallback: a silently-serialized projection is exactly the
        # defect the serialized-backward corpus entry plants — if the
        # overlap the config asked for can't be hosted, say so
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            f"tp_overlap_chunks={chunks}: chunked collective-matmul overlap "
            f"fell back to the serial matmul ({type(e).__name__}: {e}); the "
            "boundary all-reduce will be exposed")
        return x @ w


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def params_bytes(params) -> int:
    return sum(int(np.prod(p.shape)) * p.dtype.itemsize for p in jax.tree.leaves(params))


def sharded_bytes(tree) -> int:
    """PER-DEVICE resident bytes of a pytree of committed jax Arrays: each
    leaf is priced at its shard shape (``sharding.shard_shape``), so a
    tensor-sharded KV block pool is counted once per chip, not once per
    logical array. Leaves without a sharding (host numpy, abstract shapes)
    fall back to their full size — on a 1-device mesh the two agree.
    This is what the serving engine's ``pool_bytes`` reports: the HBM a
    chip actually spends, the number the memory law is written against."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:  # pragma: no cover - exotic shardings
                pass
        total += int(np.prod(shape)) * np.dtype(leaf.dtype).itemsize
    return total
