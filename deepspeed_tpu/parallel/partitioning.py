"""Logical-axis partitioning: map parameter logical axes -> mesh axes.

This replaces the reference's partitioned-tensor bookkeeping (`ds_tensor`,
`ds_id`, partition/allgather primitives — ``runtime/zero/partition_parameters.py``)
with declarative sharding: every parameter carries a tuple of *logical* axis
names (e.g. ("embed", "mlp")), and a rules table maps logical names to mesh
axis names. GSPMD then inserts the all-gathers/reduce-scatters the reference
implements by hand.

t5x/flax use the same idea; the implementation here is our own and tuned to the
ZeRO-stage semantics described in zero/config.
"""

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxis = Union[str, Tuple[str, ...], None]


@dataclasses.dataclass
class ShardingRules:
    """Ordered logical->mesh rules; first match wins (like t5x rule lists)."""
    rules: Tuple[Tuple[str, MeshAxis], ...]

    def mesh_axes(self, logical_axes: Optional[Tuple[Optional[str], ...]]):
        if logical_axes is None:
            return P()
        table = dict(self.rules)
        out = []
        used = set()
        for name in logical_axes:
            axis = table.get(name) if name is not None else None
            # one mesh axis can only be used once per spec
            key = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
            if axis is not None and any(a in used for a in key):
                axis = None
            if axis is not None:
                used.update(key)
            out.append(tuple(axis) if isinstance(axis, list) else axis)
        while out and out[-1] is None:
            out.pop()
        return P(*out)


# Default logical-axis vocabulary used by deepspeed_tpu.models:
#   "embed"    — model hidden dim
#   "vocab"    — vocabulary dim
#   "mlp"      — MLP intermediate dim
#   "heads"    — attention heads dim
#   "kv"       — per-head dim
#   "qkv"      — fused qkv output dim
#   "expert"   — expert index dim (MoE stacked experts)
#   "unmodeled"— small params (biases, norms)
#   "layers"   — scanned-layer stacking dim

def make_rules(zero_stage: int, tp: bool = True, pipe: bool = False,
               fsdp_axis: str = "fsdp", tensor_axis: str = "tensor") -> ShardingRules:
    """Build the rules table realizing a ZeRO stage + optional TP + PP.

    stage <= 2: params replicated across DP — logical axes map only to tensor.
    stage == 3: the largest logical dim additionally shards over `fsdp`
    (all-gather-on-use inserted by GSPMD = ZeRO-3 fetch/release).
    pipe: the stacked `layers` dim shards over `pipe` (= the reference's
    PipelineModule layer partitioning, as a sharding choice).
    """
    t = tensor_axis if tp else None
    layers_axis = "pipe" if pipe else None
    if zero_stage >= 3:
        rules = (
            ("vocab", (fsdp_axis, t) if t else fsdp_axis),
            ("embed", fsdp_axis),
            ("mlp", t if t else fsdp_axis),
            ("heads", t if t else fsdp_axis),
            ("qkv", t if t else fsdp_axis),
            ("kv", None),
            ("expert", "expert"),
            ("layers", layers_axis),
            ("unmodeled", None),
        )
    else:
        rules = (
            ("vocab", t),
            ("embed", None),
            ("mlp", t),
            ("heads", t),
            ("qkv", t),
            ("kv", None),
            ("expert", "expert"),
            ("layers", layers_axis),
            ("unmodeled", None),
        )
    return ShardingRules(rules=tuple((k, v) for k, v in rules))


# --------------------------------------------------------------------------
# Param metadata pytrees
# --------------------------------------------------------------------------

def logical_to_sharding(logical_tree, mesh: Mesh, rules: ShardingRules):
    """Map a pytree of logical-axis tuples to a pytree of NamedSharding."""
    def one(axes):
        return NamedSharding(mesh, rules.mesh_axes(axes))
    return jax.tree.map(one, logical_tree, is_leaf=lambda x: x is None or isinstance(x, tuple))


def spec_tree(logical_tree, rules: ShardingRules):
    def one(axes):
        return rules.mesh_axes(axes)
    return jax.tree.map(one, logical_tree, is_leaf=lambda x: x is None or isinstance(x, tuple))


def shard_params(params, shardings):
    return jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def params_bytes(params) -> int:
    return sum(int(np.prod(p.shape)) * p.dtype.itemsize for p in jax.tree.leaves(params))
