"""Mesh planner: world size + parallelism degrees -> a named `jax.sharding.Mesh`.

Reference equivalents: ``deepspeed/utils/groups.py:45`` (DP/MP/EP group
factory), ``runtime/pipe/topology.py:9`` (ProcessTopology rank grid). On TPU
the rank grid IS the mesh: process groups become named mesh axes, and group
collectives become `jax.lax` ops over those axis names.

Axis names (fixed vocabulary):
  pipe   — pipeline stages (outermost: cross-slice/DCN friendly)
  data   — pure data parallel (replicated params)
  fsdp   — ZeRO/FSDP data parallel (params/grads/opt sharded)
  seq    — sequence/context parallelism (ring attention)
  tensor — tensor-model parallelism (megatron-style col/row)
  expert — expert parallelism for MoE (folded from data×fsdp at dispatch time)

ZeRO stages map onto (data, fsdp): stage 0-2 put all DP on "data"; stage 3
puts it on "fsdp" (params sharded there). Stage 1/2 shard optimizer
state/grads over "data" without sharding params — see zero/partition rules.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from deepspeed_tpu.utils.logging import logger

# canonical axis order, outermost first — pipe outermost so that PP crosses
# the slowest links (DCN) and tensor innermost so TP rides fastest ICI links.
AXIS_ORDER = ("pipe", "data", "fsdp", "expert", "seq", "tensor")

# the axes that together carry the global batch dim (engine._batch_spec and
# the model-side activation constraint must agree on this set)
BATCH_AXES = ("data", "fsdp", "expert")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Resolved parallelism degrees for the current world size."""
    pipe: int = 1
    data: int = 1
    fsdp: int = 1
    expert: int = 1    # expert parallelism: carved out of the dp degree
    seq: int = 1
    tensor: int = 1

    @property
    def world_size(self) -> int:
        return (self.pipe * self.data * self.fsdp * self.expert * self.seq
                * self.tensor)

    @property
    def dp_world_size(self) -> int:
        """Total data-parallel degree: expert groups also consume distinct
        data (= the reference's expert-data-parallel groups)."""
        return self.data * self.fsdp * self.expert

    def axis_sizes(self) -> Dict[str, int]:
        return {"pipe": self.pipe, "data": self.data, "fsdp": self.fsdp,
                "expert": self.expert, "seq": self.seq, "tensor": self.tensor}

    def describe(self) -> str:
        return "x".join(f"{k}={v}" for k, v in self.axis_sizes().items() if v > 1) or "single"


def plan_from_config(config, world_size: int) -> MeshPlan:
    """Derive the mesh plan from config + world size.

    Explicit `mesh.axes` wins; otherwise degrees come from
    pipeline.stages / tensor_parallel.tp_size / sequence_parallel.sp_size /
    moe.expert_parallel_size, and the remaining factor becomes data or fsdp
    depending on the ZeRO stage (stage>=3 -> fsdp, else data).
    """
    explicit = dict(config.mesh.axes or {})
    if explicit:
        ep_default = (config.moe.expert_parallel_size
                      if config.moe.enabled else 1)
        plan = MeshPlan(
            pipe=explicit.get("pipe", 1), data=explicit.get("data", 1),
            fsdp=explicit.get("fsdp", 1),
            expert=explicit.get("expert", ep_default),
            seq=explicit.get("seq", 1), tensor=explicit.get("tensor", 1))
        if plan.world_size != world_size:
            raise ValueError(f"mesh.axes product {plan.world_size} != world size {world_size}")
        return plan

    pp = max(1, config.pipeline.stages)
    tp = max(1, config.tensor_parallel.tp_size)
    sp = max(1, config.sequence_parallel.sp_size)
    denom = pp * tp * sp
    if world_size % denom != 0:
        raise ValueError(f"world size {world_size} not divisible by pipe({pp})*tensor({tp})*seq({sp})")
    dp = world_size // denom
    ep = max(1, config.moe.expert_parallel_size) if config.moe.enabled else 1
    if dp % ep != 0:
        raise ValueError(f"expert_parallel_size {ep} must divide dp degree {dp}")
    dp //= ep
    stage = config.zero_optimization.stage
    if stage >= 3:
        data, fsdp = 1, dp
    else:
        data, fsdp = dp, 1
    return MeshPlan(pipe=pp, data=data, fsdp=fsdp, expert=ep, seq=sp, tensor=tp)


def build_mesh(plan: MeshPlan, devices: Optional[List] = None) -> Mesh:
    """Build the device mesh.

    Uses `jax.experimental.mesh_utils.create_device_mesh` when it can (it
    optimizes assignment for the TPU torus so that the innermost axes land on
    the fastest ICI rings); falls back to a plain reshape.
    """
    import jax
    devices = devices if devices is not None else jax.devices()
    shape = tuple(getattr(plan, ax) for ax in AXIS_ORDER)
    n = int(np.prod(shape))
    if n != len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        if len(devices) > 1 and devices[0].platform == "tpu":
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        else:
            dev_array = np.asarray(devices).reshape(shape)
    except Exception as e:  # pragma: no cover - defensive
        logger.warning(f"mesh_utils failed ({e}); using naive device order")
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def single_device_mesh() -> Mesh:
    import jax
    return Mesh(np.asarray(jax.devices()[:1]).reshape((1,) * len(AXIS_ORDER)), AXIS_ORDER)


# --------------------------------------------------------------------------
# Topology queries (reference: runtime/pipe/topology.py ProcessTopology API)
# --------------------------------------------------------------------------

class Topology:
    """Rank-grid queries over the mesh, mirroring the reference's
    ``ProcessTopology`` (``runtime/pipe/topology.py:9``): get_rank(axis=coord),
    get_axis_comm_lists, filter_match."""

    def __init__(self, plan: MeshPlan):
        self.plan = plan
        self.axes = [ax for ax in AXIS_ORDER]
        self.dims = [getattr(plan, ax) for ax in AXIS_ORDER]

    def world_size(self) -> int:
        return int(np.prod(self.dims))

    def get_rank(self, **coords) -> int:
        idx = [coords.get(ax, 0) for ax in self.axes]
        return int(np.ravel_multi_index(idx, self.dims))

    def get_coord(self, rank: int) -> Dict[str, int]:
        unraveled = np.unravel_index(rank, self.dims)
        return {ax: int(c) for ax, c in zip(self.axes, unraveled)}

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that differ only along `axis` (the reference builds
        torch process groups from these; we only need them for tests/tools)."""
        ai = self.axes.index(axis)
        groups = {}
        for rank in range(self.world_size()):
            coord = list(np.unravel_index(rank, self.dims))
            key = tuple(c for i, c in enumerate(coord) if i != ai)
            groups.setdefault(key, []).append(rank)
        return [sorted(v) for _, v in sorted(groups.items())]

    def filter_match(self, **coords) -> List[int]:
        out = []
        for rank in range(self.world_size()):
            c = self.get_coord(rank)
            if all(c[k] == v for k, v in coords.items()):
                out.append(rank)
        return out
