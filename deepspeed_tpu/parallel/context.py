"""Ambient parallel context.

The engine publishes its mesh/plan here so that model-internal ops (ring
attention over the `seq` axis, MoE dispatch) can build shard_maps without
threading the mesh through every model signature. Mirrors how the reference
publishes process groups via the global ``deepspeed.utils.groups`` registry
(``utils/groups.py``) rather than passing them explicitly.
"""

from typing import Optional

from jax.sharding import Mesh

_MESH: Optional[Mesh] = None
_PLAN = None


def set_parallel_context(mesh: Mesh, plan) -> None:
    global _MESH, _PLAN
    _MESH = mesh
    _PLAN = plan


def current_mesh() -> Optional[Mesh]:
    return _MESH


def current_plan():
    return _PLAN


def seq_parallel_degree() -> int:
    return getattr(_PLAN, "seq", 1) if _PLAN is not None else 1


def physical_mesh_env():
    """(physical mesh | None, {axis: size}, shard_map-bound axis names) of
    the ambient trace context.

    The one sanctioned home for the jax._src introspection the model-internal
    sharding hints need: ``thread_resources.env.physical_mesh`` is the mesh
    the surrounding ``with mesh:`` / jit established; the bound set is the
    axes a surrounding ``shard_map`` has already made manual (constraining
    over those would double-partition). Both surfaces shift between jax
    releases — keep every consumer on this helper so a rename breaks ONE
    place."""
    try:
        from jax._src import mesh as _mesh_lib
        env_mesh = _mesh_lib.thread_resources.env.physical_mesh
    except Exception:  # pragma: no cover - jax internals drift
        return None, {}, set()
    if env_mesh is None or env_mesh.empty:
        return None, {}, set()
    try:
        from jax._src import core as _core
        bound = set(getattr(_core.get_axis_env(), "axis_sizes", {}) or {})
    except Exception:  # pragma: no cover - jax internals drift
        bound = set()
    return env_mesh, dict(env_mesh.shape), bound
