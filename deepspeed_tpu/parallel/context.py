"""Ambient parallel context.

The engine publishes its mesh/plan here so that model-internal ops (ring
attention over the `seq` axis, MoE dispatch) can build shard_maps without
threading the mesh through every model signature. Mirrors how the reference
publishes process groups via the global ``deepspeed.utils.groups`` registry
(``utils/groups.py``) rather than passing them explicitly.
"""

from typing import Optional

from jax.sharding import Mesh

_MESH: Optional[Mesh] = None
_PLAN = None


def set_parallel_context(mesh: Mesh, plan) -> None:
    global _MESH, _PLAN
    _MESH = mesh
    _PLAN = plan


def current_mesh() -> Optional[Mesh]:
    return _MESH


def current_plan():
    return _PLAN


def seq_parallel_degree() -> int:
    return getattr(_PLAN, "seq", 1) if _PLAN is not None else 1
