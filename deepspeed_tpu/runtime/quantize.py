"""MoQ — Mixture-of-Quantization training (eigenvalue-scheduled precision).

Reference: ``deepspeed/runtime/quantize.py:11`` (Quantizer: start_bits ->
target_bits over quantize_period steps) + ``engine.py:1816`` (eigenvalue
events feeding the per-layer schedule): layers whose loss curvature (top
Hessian eigenvalue) is larger keep high precision LONGER.

TPU-native: the per-layer bit-widths are a [L] host array injected into the
jitted step as a traced side-channel (like the PLD theta), so schedule
updates and eigenvalue refreshes never recompile; the quantize-dequantize
itself is a straight-through estimator with traced bits.
"""

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _ste_quant_traced_bits(x, bits):
    """Symmetric fake-quant with TRACED per-call bits (scalar). STE grad."""
    levels = jnp.power(2.0, bits - 1.0) - 1.0
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.maximum(amax / levels, 1e-12)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -levels, levels)
    xq = (xq * scale).astype(x.dtype)
    return x + jax.lax.stop_gradient(xq - x)


class MoQ:
    """Quantization-period scheduler + traced param transform.

    bits(l, t) = clip(start_bits - floor((t - offset) / period_l),
                      target_bits, start_bits), period_l = quantize_period
    scaled per layer by its normalized eigenvalue (larger curvature ->
    longer period -> quantizes later), matching the reference's MoQ
    eigenvalue semantics.
    """

    def __init__(self, config: Dict[str, Any], num_layers: int):
        bits_cfg = config.get("quantize_bits", {})
        sched = config.get("quantize_schedule", {})
        self.start_bits = int(bits_cfg.get("start_bits", 16))
        self.target_bits = int(bits_cfg.get("target_bits", 8))
        self.period = max(1, int(sched.get("quantize_period", 100)))
        self.offset = int(sched.get("schedule_offset", 0))
        ev = config.get("eigenvalue", {}) or {}
        self.ev_enabled = bool(ev.get("enabled", False))
        self.ev_cfg = ev
        self.num_layers = num_layers
        # period multiplier per layer; 1.0 until eigenvalues arrive
        self._period_scale = np.ones(num_layers, np.float64)
        self._ev_refresh_every = max(
            1, int(ev.get("gas_boundary_resolution", 1)) * self.period)
        self._last_ev_step = -1

    # ------------------------------------------------------------------
    def bits(self, step: int) -> np.ndarray:
        """[L] float32 bit-widths at global step `step` (host side)."""
        t = max(0, step - self.offset)
        periods = np.maximum(1.0, self.period * self._period_scale)
        drop = np.floor(t / periods)
        b = np.clip(self.start_bits - drop, self.target_bits,
                    self.start_bits)
        return b.astype(np.float32)

    def wants_eigenvalues(self, step: int) -> bool:
        return (self.ev_enabled and step >= self.offset
                and (self._last_ev_step < 0
                     or step - self._last_ev_step >= self._ev_refresh_every))

    def update_eigenvalues(self, evs: np.ndarray, step: int):
        """evs: [L] top |eigenvalue| per layer block. Normalized so the
        mean layer keeps the base period; high-curvature layers stretch."""
        evs = np.maximum(np.asarray(evs, np.float64), 1e-12)
        self._period_scale = evs / evs.mean()
        self._last_ev_step = step
        logger.info(f"MoQ eigenvalues at step {step}: period scales "
                    f"{np.round(self._period_scale, 2).tolist()}")

    # ------------------------------------------------------------------
    def apply(self, params, bits_arr):
        """Traced transform: fake-quant each stacked layer leaf with its
        layer's bit-width. bits_arr: [L] traced float."""
        def one(leaf):
            if not hasattr(leaf, "ndim") or leaf.ndim < 3 or \
                    leaf.shape[0] != self.num_layers:
                return leaf
            return jax.vmap(_ste_quant_traced_bits)(leaf, bits_arr)
        out = dict(params)
        out["layers"] = {k: one(v) for k, v in params["layers"].items()}
        return out

    # ------------------------------------------------------------------
    def layer_eigenvalues(self, loss_fn, params, batch, rng=None,
                          max_iter: Optional[int] = None) -> np.ndarray:
        """Per-layer top |eigenvalue| via block-restricted power iteration
        (reference: Eigenvalue.compute_eigenvalue per module block).
        loss_fn(params, batch) must be a STABLE callable (e.g. the
        ModelSpec's loss_fn) — the jitted HVP is cached on this object so
        refreshes retrace nothing."""
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
        ev = Eigenvalue(
            max_iterations=max_iter or int(self.ev_cfg.get("max_iter", 20)),
            tol=float(self.ev_cfg.get("tol", 1e-2)),
            stability=float(self.ev_cfg.get("stability", 1e-6)))
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        out = np.zeros(self.num_layers)

        # one power iteration over the whole stacked-layer block; per-layer
        # curvature read off the converged (v, Hv) pair as a blockwise
        # Rayleigh quotient |v_l . Hv_l| / (v_l . v_l) — L layers for the
        # cost of ONE iteration chain instead of L separate ones
        if getattr(self, "_hvp_jit", None) is None or \
                self._hvp_for is not loss_fn:
            def hvp(params_, batch_, v):
                def block_loss(layer_stack):
                    p = dict(params_)
                    p["layers"] = layer_stack
                    return loss_fn(p, batch_)
                return jax.jvp(jax.grad(block_loss),
                               (params_["layers"],), (v,))[1]
            self._hvp_jit = jax.jit(hvp)
            self._hvp_for = loss_fn
        hvp = lambda v: self._hvp_jit(params, batch, v)  # noqa: E731

        def normalize(t):
            n = jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                             for x in jax.tree.leaves(t)) + ev.stability)
            return jax.tree.map(
                lambda x: (x.astype(jnp.float32) / n).astype(x.dtype), t)

        leaves, treedef = jax.tree.flatten(params["layers"])
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree.unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.float32).astype(l.dtype)
            for k, l in zip(keys, leaves)])
        for _ in range(ev.max_iterations):
            v = normalize(hvp(normalize(v)))
        v = normalize(v)
        hv = hvp(v)
        for li in range(self.num_layers):
            num = den = 0.0
            for x, y in zip(jax.tree.leaves(v), jax.tree.leaves(hv)):
                xl = np.asarray(jax.device_get(x[li]), np.float64)
                yl = np.asarray(jax.device_get(y[li]), np.float64)
                num += float(np.sum(xl * yl))
                den += float(np.sum(xl * xl))
            out[li] = abs(num) / max(den, 1e-12)
        return out


def build_moq(config: Dict[str, Any], num_layers: int) -> Optional[MoQ]:
    if not config or not config.get("enabled", False):
        return None
    moq = MoQ(config, num_layers)
    logger.info(f"MoQ: {moq.start_bits}->{moq.target_bits} bits over "
                f"period {moq.period} (offset {moq.offset})"
                + (", eigenvalue-scheduled" if moq.ev_enabled else ""))
    return moq
