"""Data efficiency pipeline: curriculum learning, distributed sampling,
Random-LTD token dropping.

Reference: ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py:9``
(CurriculumScheduler — fixed_linear/fixed_root/fixed_discrete/custom
difficulty schedules), ``data_sampler.py:33`` (DeepSpeedDataSampler /
distributed sampling), and ``data_routing/basic_layer.py`` (Random-LTD:
middle layers process a random subset of tokens, scattered back into the
residual stream).

TPU-native notes: difficulty and kept-token counts are SHAPES on TPU, so the
schedulers quantize their outputs (multiples of `step`) and the engine re-jits
per distinct value — a handful of compiles over a run, each cached. Random-LTD
gather/scatter are static-shape `jnp.take_along_axis` ops XLA vectorizes.
"""

import dataclasses
import math
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


class CurriculumScheduler:
    """Difficulty schedule (reference: curriculum_scheduler.py:9).

    schedule_type:
      fixed_linear:   difficulty grows linearly to max over total_curriculum_step
      fixed_root:     grows as (step/total)^(1/root_degree)
      fixed_discrete: explicit difficulty[] + max_step[] breakpoints
      custom:         user callable step -> difficulty
    Difficulties are rounded to `difficulty_step` (shape bucketing on TPU).
    """

    def __init__(self, cfg: Dict[str, Any],
                 custom_fn: Optional[Callable[[int], int]] = None):
        self.type = cfg.get("schedule_type", cfg.get("curriculum_type_schedule",
                                                     "fixed_linear"))
        self.min_difficulty = int(cfg.get("min_difficulty", 8))
        self.max_difficulty = int(cfg.get("max_difficulty", 1024))
        sc = cfg.get("schedule_config", {})
        self.total_step = int(sc.get("total_curriculum_step", 1000))
        self.difficulty_step = int(sc.get("difficulty_step", 8))
        self.root_degree = int(sc.get("root_degree", 2))
        self.discrete_difficulties = list(sc.get("difficulty", []))
        self.discrete_steps = list(sc.get("max_step", []))
        self.custom_fn = custom_fn
        if self.type == "custom" and custom_fn is None:
            raise ValueError("custom curriculum schedule needs a callable")
        self.current = self.min_difficulty

    def _raw(self, step: int) -> float:
        if self.type == "fixed_linear":
            frac = min(1.0, step / max(1, self.total_step))
        elif self.type == "fixed_root":
            frac = min(1.0, (step / max(1, self.total_step))
                       ** (1.0 / self.root_degree))
        elif self.type == "fixed_discrete":
            for d, s in zip(self.discrete_difficulties, self.discrete_steps):
                if step <= s:
                    return float(d)
            return float(self.discrete_difficulties[-1]
                         if self.discrete_difficulties else self.max_difficulty)
        elif self.type == "custom":
            return float(self.custom_fn(step))
        else:
            raise ValueError(f"unknown curriculum schedule {self.type!r}")
        return (self.min_difficulty
                + frac * (self.max_difficulty - self.min_difficulty))

    def update_difficulty(self, step: int) -> int:
        d = self._raw(step)
        q = self.difficulty_step
        d = int(min(self.max_difficulty,
                    max(self.min_difficulty, math.ceil(d / q) * q)))
        self.current = d
        return d

    def get_current_difficulty(self) -> int:
        return self.current


def apply_seqlen_curriculum(batch: Dict[str, Any], difficulty: int
                            ) -> Dict[str, Any]:
    """Truncate the sequence dim to the current difficulty (reference:
    megatron curriculum truncates input/labels/mask the same way)."""
    out = {}
    for k, v in batch.items():
        if hasattr(v, "ndim") and v.ndim >= 2 and v.shape[1] > difficulty:
            out[k] = v[:, :difficulty]
        else:
            out[k] = v
    return out


class DistributedSampler:
    """Per-replica index sampler (reference: ``runtime/dataloader.py``
    DistributedSampler usage + ``data_sampler.py:33``).

    Under SPMD one *process* feeds all local devices, so num_replicas/rank
    default to jax.process_count()/process_index() — each host samples its
    contiguous shard of the epoch permutation."""

    def __init__(self, dataset_len: int, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True):
        if num_replicas is None or rank is None:
            import jax
            num_replicas = num_replicas or jax.process_count()
            rank = rank if rank is not None else jax.process_index()
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.n = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        if drop_last:
            self.num_samples = self.n // num_replicas
        else:
            self.num_samples = math.ceil(self.n / num_replicas)
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        order = np.arange(self.n)
        if self.shuffle:
            np.random.default_rng(self.seed + self.epoch).shuffle(order)
        if not self.drop_last and self.n % self.num_replicas:
            pad = self.num_replicas * self.num_samples - self.n
            order = np.concatenate([order, order[:pad]])
        shard = order[self.rank * self.num_samples:
                      (self.rank + 1) * self.num_samples]
        return iter(shard.tolist())


# ---------------------------------------------------------------------------
# Random-LTD (random layerwise token dropping)
# ---------------------------------------------------------------------------

class RandomLTDScheduler:
    """Kept-token schedule (reference: ``data_pipeline/data_routing/
    scheduler.py`` RandomLTDScheduler — linearly increases the kept-token
    count from min to the full sequence over a step budget)."""

    def __init__(self, cfg: Dict[str, Any]):
        rl = cfg.get("random_ltd", cfg)
        self.min_value = int(rl.get("random_ltd_schedule", {}).get(
            "min_value", rl.get("min_value", 128)))
        self.max_value = int(rl.get("random_ltd_schedule", {}).get(
            "max_value", rl.get("max_value", 2048)))
        sched = rl.get("random_ltd_schedule", rl)
        self.total_steps = int(sched.get("schedule_config", sched).get(
            "total_layer_tokens_steps", sched.get("total_steps", 1000)))
        self.step_size = int(sched.get("schedule_config", sched).get(
            "seq_step", 64))

    def kept_tokens(self, step: int, seq_len: int) -> int:
        frac = min(1.0, step / max(1, self.total_steps))
        k = self.min_value + frac * (self.max_value - self.min_value)
        k = int(min(seq_len, max(self.min_value,
                                 math.ceil(k / self.step_size) * self.step_size)))
        return min(k, seq_len)


def random_ltd_layer(x, layer_fn, keep: int, rng, *args, **kwargs):
    """Run `layer_fn` on a random `keep`-token subset of x [B,S,H]; tokens
    not selected pass through unchanged (reference: data_routing/
    basic_layer.py RandomLayerTokenDrop forward).

    Static-shape: `keep` is a Python int; selection is a per-row random
    permutation prefix, gathered with take_along_axis and scattered back.
    """
    import jax
    import jax.numpy as jnp
    B, S, H = x.shape
    if keep >= S:
        return layer_fn(x, *args, **kwargs)
    # per-row random selection WITHOUT replacement: argsort of uniforms
    u = jax.random.uniform(rng, (B, S))
    sel = jnp.argsort(u, axis=1)[:, :keep]                      # [B, keep]
    sel_sorted = jnp.sort(sel, axis=1)                          # keep order
    sub = jnp.take_along_axis(x, sel_sorted[..., None], axis=1)  # [B,keep,H]
    kwargs = dict(kwargs)
    # rotary/learned positions must be the TRUE token positions of the
    # selected subset; a padding mask is gathered the same way
    pos = kwargs.get("positions")
    kwargs["positions"] = (sel_sorted if pos is None
                           else jnp.take_along_axis(pos, sel_sorted, axis=1))
    if kwargs.get("mask") is not None:
        kwargs["mask"] = jnp.take_along_axis(kwargs["mask"], sel_sorted,
                                             axis=1)
    out = layer_fn(sub, *args, **kwargs)
    y = out[0] if isinstance(out, tuple) else out
    full = x.at[jnp.arange(B)[:, None], sel_sorted].set(y.astype(x.dtype))
    if isinstance(out, tuple):
        return (full,) + out[1:]
    return full


# --------------------------------------------------------------------------
# Indexed dataset + offline data analyzer
# (reference: runtime/data_pipeline/data_sampling/indexed_dataset.py — the
# Megatron mmap .bin/.idx format — and data_analyzer.py:18 DataAnalyzer:
# an offline pass computing per-sample metrics consumed by the curriculum
# sampler)
# --------------------------------------------------------------------------

class IndexedDataset:
    """mmap-backed variable-length token dataset: `prefix.bin` holds the
    concatenated int32 token streams, `prefix.idx` the (offset, length)
    table. Random access costs one mmap slice — no loading, no pickling."""

    MAGIC = 0x44535450  # "DSTP"

    def __init__(self, prefix: str):
        idx = np.fromfile(f"{prefix}.idx", dtype=np.int64)
        if len(idx) < 3 or idx[0] != self.MAGIC:
            raise ValueError(f"{prefix}.idx is not an indexed dataset")
        n = int(idx[1])
        self._offsets = idx[2:2 + n + 1]
        self._data = np.memmap(f"{prefix}.bin", dtype=np.int32, mode="r")

    def __len__(self):
        return len(self._offsets) - 1

    def __getitem__(self, i: int) -> np.ndarray:
        return np.asarray(self._data[self._offsets[i]:self._offsets[i + 1]])

    @property
    def lengths(self) -> np.ndarray:
        return np.diff(self._offsets)


def write_indexed_dataset(samples, prefix: str) -> int:
    """samples: iterable of 1-D int token arrays (streamed; O(1) memory).
    Returns the sample count."""
    offsets = [0]
    with open(f"{prefix}.bin", "wb") as f:
        for s in samples:
            arr = np.ascontiguousarray(np.asarray(s, np.int32).reshape(-1))
            arr.tofile(f)
            offsets.append(offsets[-1] + arr.size)
    n = len(offsets) - 1
    header = np.array([IndexedDataset.MAGIC, n], np.int64)
    np.concatenate([header, np.asarray(offsets, np.int64)]).tofile(
        f"{prefix}.idx")
    return n


class DataAnalyzer:
    """Offline per-sample metric pass (reference: data_analyzer.py:18).

    metrics: {name: fn(sample_tokens) -> float}. `run` writes, per metric,
    `<name>_values.npy` (value per sample id) and `<name>_order.npy`
    (sample ids sorted easiest-first) into out_dir — exactly the artifacts
    the curriculum sampler consumes."""

    BUILTIN = {"seqlen": lambda toks: float(len(toks)),
               "vocab_rarity": lambda toks: float(
                   np.mean(np.asarray(toks, np.float64)))}

    def __init__(self, metrics: Optional[Dict[str, Any]] = None):
        self.metrics = metrics or {"seqlen": self.BUILTIN["seqlen"]}

    def run(self, dataset, out_dir: str) -> Dict[str, str]:
        os.makedirs(out_dir, exist_ok=True)
        values = {name: [] for name in self.metrics}
        for i in range(len(dataset)):
            sample = dataset[i]
            toks = sample["input_ids"] if isinstance(sample, dict) else sample
            for name, fn in self.metrics.items():
                values[name].append(fn(toks))
        paths = {}
        for name, vals in values.items():
            v = np.asarray(vals, np.float64)
            order = np.argsort(v, kind="stable").astype(np.int64)
            np.save(os.path.join(out_dir, f"{name}_values.npy"), v)
            np.save(os.path.join(out_dir, f"{name}_order.npy"), order)
            paths[name] = out_dir
        return paths


class CurriculumSampler:
    """Difficulty-gated sampler over a DataAnalyzer index (reference:
    data_sampling/data_sampler.py DeepSpeedDataSampler): at each step, draws
    batches uniformly from the easiest samples whose metric value is within
    the scheduler's current difficulty."""

    def __init__(self, metric_dir: str, metric: str,
                 scheduler: CurriculumScheduler, batch_size: int, *,
                 rank: int = 0, world_size: int = 1, seed: int = 0):
        self.values = np.load(os.path.join(metric_dir,
                                           f"{metric}_values.npy"))
        self.order = np.load(os.path.join(metric_dir, f"{metric}_order.npy"))
        self._sorted_vals = self.values[self.order]  # once, not per step
        self.scheduler = scheduler
        self.batch_size = batch_size
        self.rank, self.world = rank, world_size
        # every rank MUST draw the identical stream (seed only): the global
        # batch is the shared draw, each rank takes its strided rows —
        # per-rank seeds would duplicate/skip samples across the dp group
        self._rng = np.random.default_rng(seed)

    def eligible(self, step: int) -> np.ndarray:
        d = self.scheduler.update_difficulty(step)
        cutoff = int(np.searchsorted(self._sorted_vals, d, side="right"))
        cutoff = max(cutoff, self.batch_size * self.world)
        return self.order[:cutoff]

    def sample(self, step: int) -> np.ndarray:
        """Sample ids for this rank's micro-batch at `step`."""
        pool = self.eligible(step)
        picks = self._rng.choice(pool, size=self.batch_size * self.world,
                                 replace=len(pool) < self.batch_size * self.world)
        return picks[self.rank::self.world]
