"""Sharded checkpoint save/load.

Reference: ``runtime/engine.py`` ``save_checkpoint:2817`` / ``load_checkpoint:
2512`` (per-rank ZeRO shards, `latest` tag file, tag validation,
client_state), pluggable ``CheckpointEngine`` (``runtime/checkpoint_engine/``),
and the offline universal-checkpoint tooling (``deepspeed/checkpoint/``,
``utils/zero_to_fp32.py``).

TPU-native: Orbax/TensorStore writes each array sharded and restores it under
*any* mesh — so elastic resume and "universal checkpoint" are by-construction
(SURVEY §5: "Orbax sharded async checkpoint with logical-axis metadata =
universal checkpoint by construction"). The DeepSpeed directory contract is
preserved: <dir>/<tag>/..., a `latest` file, and a client_state payload.
"""

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger

LATEST_FILE = "latest"


def _pointer_file(path: str) -> str:
    return f"{path}.current"


def _read_pointer(path: str) -> Optional[str]:
    """Absolute path of the live version dir for `path`, or None."""
    try:
        with open(_pointer_file(path)) as f:
            name = f.read().strip()
        return os.path.join(os.path.dirname(path), name)
    except FileNotFoundError:
        return None


def _write_pointer(path: str, version_name: str) -> None:
    """Atomically publish version_name as the live version of `path`."""
    ptr = _pointer_file(path)
    tmp = f"{ptr}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(version_name)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, ptr)


def _resolve_pointer(path: str) -> str:
    """Follow `<path>.current` if present; fall back to `path` itself
    (legacy layout and checkpoints written by other tools)."""
    target = _read_pointer(path)
    if target is not None and os.path.exists(target):
        return target
    return path


class CheckpointEngine:
    """Base checkpoint engine (reference: checkpoint_engine.py:6). The Orbax
    engine below is the default; TorchCheckpointEngine's role (one file per
    rank) has no TPU equivalent — sharding lives inside TensorStore."""

    def save(self, state, path: str, on_complete=None):
        raise NotImplementedError

    def load(self, path: str, template=None, shardings=None):
        raise NotImplementedError

    def wait(self):
        return None

    def commit(self, tag: str):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    def __init__(self, async_save: bool = False):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.async_save = async_save
        self._pending = None
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler()) \
            if async_save else ocp.StandardCheckpointer()
        if async_save:
            # the final save of a run must still land: finalize (tmp->path
            # swap, meta.json, `latest`) at interpreter exit if nobody waited
            import atexit
            atexit.register(self.wait)

    def save(self, state, path: str, on_complete=None):
        # Crash-safe overwrite via a pointer file: the state is written to a
        # unique versioned dir (`<path>-v<token>`) and `<path>.current` is
        # atomically os.replace()'d to name it only once the write is durable.
        # A crash at ANY point leaves the pointer naming the previous good
        # version — there is no window where `latest` points at nothing.
        # For async_save the publish + on_complete are deferred to wait(),
        # so training overlaps the TensorStore write.
        if self._pending is not None:
            self.wait()  # finalize the previous in-flight save first
        path = os.path.abspath(path)
        prev = _read_pointer(path)
        token = f"{os.getpid()}-{int.from_bytes(os.urandom(4), 'big'):08x}"
        vdir = f"{path}-v{token}"
        self._ckptr.save(vdir, state)
        self._pending = (vdir, path, prev, on_complete)
        if not self.async_save:
            self.wait()

    def wait(self):
        pending, self._pending = getattr(self, "_pending", None), None
        try:
            self._ckptr.wait_until_finished()
        except AttributeError:
            pass
        except Exception:
            # failed async write: drop the partial version dir, never publish
            if pending is not None:
                shutil.rmtree(pending[0], ignore_errors=True)
            raise
        if pending is None:
            return
        vdir, path, prev, on_complete = pending
        _write_pointer(path, os.path.basename(vdir))  # atomic publish
        if prev is not None and prev != vdir and os.path.exists(prev):
            shutil.rmtree(prev, ignore_errors=True)
        if os.path.isdir(path):  # legacy un-versioned layout superseded
            shutil.rmtree(path, ignore_errors=True)
        if on_complete is not None:
            on_complete()

    def load(self, path: str, template=None, shardings=None):
        path = _resolve_pointer(os.path.abspath(path))
        if template is not None and shardings is not None:
            abstract = jax.tree.map(
                lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s),
                template, shardings)
            return self._ckptr.restore(path, abstract)
        if template is not None:
            abstract = jax.tree.map(
                lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), template)
            return self._ckptr.restore(path, abstract)
        return self._ckptr.restore(path)


def save_checkpoint(save_dir: str, tag: str, state, *,
                    client_state: Optional[Dict[str, Any]] = None,
                    config_dict: Optional[Dict[str, Any]] = None,
                    engine: Optional[CheckpointEngine] = None,
                    save_latest: bool = True) -> str:
    """DeepSpeed directory contract: save_dir/tag/{state,meta.json}; plus
    save_dir/latest containing the tag."""
    engine = engine or OrbaxCheckpointEngine()
    ckpt_path = os.path.join(save_dir, str(tag))
    os.makedirs(save_dir, exist_ok=True)

    def finalize():
        # runs only after the state dir is durable (possibly async)
        meta = {
            "tag": str(tag),
            "client_state": client_state or {},
            "config": config_dict or {},
            "world_size": jax.device_count(),
            "framework_version": "deepspeed_tpu-0.1",
        }
        with open(os.path.join(ckpt_path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
        logger.info(f"saved checkpoint {ckpt_path}")

    engine.save(state, os.path.join(ckpt_path, "state"), on_complete=finalize)
    return ckpt_path


def load_checkpoint(load_dir: str, tag: Optional[str] = None, *,
                    template=None, shardings=None,
                    engine: Optional[CheckpointEngine] = None):
    """Returns (state, client_state). tag=None reads the `latest` file
    (reference: load_checkpoint:2512 latest resolution)."""
    engine = engine or OrbaxCheckpointEngine()
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            raise FileNotFoundError(f"no '{LATEST_FILE}' file under {load_dir}")
        with open(latest) as f:
            tag = f.read().strip()
    ckpt_path = os.path.join(load_dir, str(tag))
    state = engine.load(os.path.join(ckpt_path, "state"), template, shardings)
    meta_path = os.path.join(ckpt_path, "meta.json")
    client_state = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            client_state = json.load(f).get("client_state", {})
    logger.info(f"loaded checkpoint {ckpt_path}")
    return state, client_state
