"""Sharded checkpoint save/load.

Reference: ``runtime/engine.py`` ``save_checkpoint:2817`` / ``load_checkpoint:
2512`` (per-rank ZeRO shards, `latest` tag file, tag validation,
client_state), pluggable ``CheckpointEngine`` (``runtime/checkpoint_engine/``),
and the offline universal-checkpoint tooling (``deepspeed/checkpoint/``,
``utils/zero_to_fp32.py``).

TPU-native: Orbax/TensorStore writes each array sharded and restores it under
*any* mesh — so elastic resume and "universal checkpoint" are by-construction
(SURVEY §5: "Orbax sharded async checkpoint with logical-axis metadata =
universal checkpoint by construction"). The DeepSpeed directory contract is
preserved: <dir>/<tag>/..., a `latest` file, and a client_state payload.
"""

import json
import os
import shutil
from typing import Any, Dict, Iterable, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness import integrity
from deepspeed_tpu.robustness.retry import retry_io
from deepspeed_tpu.utils.logging import logger

LATEST_FILE = "latest"


def _write_small(path: str, data: str, what: str) -> None:
    """Atomic small-file write with bounded retry + the `ckpt_io` fault
    seam — one shared implementation (integrity.atomic_write) covers the
    pointer/meta/latest writers here AND the manifest/marker writers in
    robustness/integrity.py, so a transient EIO is survivable on every
    metadata file of a save, not just some of them."""
    integrity.atomic_write(path, data, what=what)


def _pointer_file(path: str) -> str:
    return f"{path}.current"


def _read_pointer(path: str) -> Optional[str]:
    """Absolute path of the live version dir for `path`, or None."""
    try:
        with open(_pointer_file(path)) as f:
            name = f.read().strip()
        return os.path.join(os.path.dirname(path), name)
    except FileNotFoundError:
        return None


def _write_pointer(path: str, version_name: str) -> None:
    """Atomically publish version_name as the live version of `path`."""
    _write_small(_pointer_file(path), version_name,
                 "checkpoint pointer publish")


def _resolve_pointer(path: str) -> str:
    """Follow `<path>.current` if present; fall back to `path` itself
    (legacy layout and checkpoints written by other tools)."""
    target = _read_pointer(path)
    if target is not None and os.path.exists(target):
        return target
    return path


class CheckpointEngine:
    """Base checkpoint engine (reference: checkpoint_engine.py:6). The Orbax
    engine below is the default; TorchCheckpointEngine's role (one file per
    rank) has no TPU equivalent — sharding lives inside TensorStore."""

    def save(self, state, path: str, on_complete=None):
        raise NotImplementedError

    def load(self, path: str, template=None, shardings=None):
        raise NotImplementedError

    def wait(self):
        return None

    def commit(self, tag: str):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    def __init__(self, async_save: bool = False):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.async_save = async_save
        self._pending = None
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler()) \
            if async_save else ocp.StandardCheckpointer()
        if async_save:
            # the final save of a run must still land: finalize (tmp->path
            # swap, meta.json, `latest`) at interpreter exit if nobody waited
            import atexit
            atexit.register(self.wait)

    def save(self, state, path: str, on_complete=None):
        # Crash-safe overwrite via a pointer file: the state is written to a
        # unique versioned dir (`<path>-v<token>`) and `<path>.current` is
        # atomically os.replace()'d to name it only once the write is durable.
        # A crash at ANY point leaves the pointer naming the previous good
        # version — there is no window where `latest` points at nothing.
        # For async_save the publish + on_complete are deferred to wait(),
        # so training overlaps the TensorStore write.
        if self._pending is not None:
            self.wait()  # finalize the previous in-flight save first
        path = os.path.abspath(path)
        prev = _read_pointer(path)
        token = f"{os.getpid()}-{int.from_bytes(os.urandom(4), 'big'):08x}"
        vdir = f"{path}-v{token}"
        self._ckptr.save(vdir, state)
        self._pending = (vdir, path, prev, on_complete)
        if not self.async_save:
            self.wait()

    def wait(self):
        pending, self._pending = getattr(self, "_pending", None), None
        try:
            self._ckptr.wait_until_finished()
        except AttributeError:
            pass
        except Exception:
            # failed async write: drop the partial version dir, never publish
            if pending is not None:
                shutil.rmtree(pending[0], ignore_errors=True)
            raise
        if pending is None:
            return
        vdir, path, prev, on_complete = pending
        _write_pointer(path, os.path.basename(vdir))  # atomic publish
        if prev is not None and prev != vdir and os.path.exists(prev):
            shutil.rmtree(prev, ignore_errors=True)
        if os.path.isdir(path):  # legacy un-versioned layout superseded
            shutil.rmtree(path, ignore_errors=True)
        if on_complete is not None:
            on_complete()

    def load(self, path: str, template=None, shardings=None):
        path = _resolve_pointer(os.path.abspath(path))
        if template is not None and shardings is not None:
            abstract = jax.tree.map(
                lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s),
                template, shardings)
            return self._ckptr.restore(path, abstract)
        if template is not None:
            abstract = jax.tree.map(
                lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), template)
            return self._ckptr.restore(path, abstract)
        return self._ckptr.restore(path)


def finalize_tag(save_dir: str, tag: str, *, save_latest: bool = True,
                 write_integrity: bool = True, checksums: bool = True,
                 keep_last_k: int = 0) -> None:
    """The integrity tail of every save: manifest -> COMMITTED -> latest ->
    retention, in that order. Shared by the Orbax path (inside finalize)
    and the infinity path (which writes its own payload files).

    The commit marker is written LAST among the tag's own files — its
    absence is the torn-save signal ``validate_tag`` keys on. `latest` is
    only a hint after this chain: a reader validates the tag it names and
    walks back when it lies."""
    ckpt_path = os.path.join(save_dir, str(tag))
    if write_integrity:
        integrity.write_manifest(ckpt_path, checksums=checksums)
        # corrupt_payload fault seam: bitrot AFTER the manifest hash
        rb_faults.mutate_seam(ckpt_path)
        # torn_save fault seam: "crash" between payload and commit marker.
        # Deliberately OUTSIDE any retry — a torn save is a process death,
        # not a transient error.
        rb_faults.io_seam("ckpt_commit", ckpt_path)
        integrity.write_commit_marker(ckpt_path)
    if save_latest:
        _write_small(os.path.join(save_dir, LATEST_FILE), str(tag),
                     "checkpoint latest publish")
    if keep_last_k:
        # never prune the tag `latest` names — with save_latest=False the
        # pointer may still name an OLDER tag than the one just saved
        protect = {str(tag)}
        try:
            with open(os.path.join(save_dir, LATEST_FILE)) as f:
                protect.add(f.read().strip())
        except OSError:
            pass
        integrity.prune_tags(save_dir, keep_last_k, protect=protect)
    logger.info(f"saved checkpoint {ckpt_path}")


def save_checkpoint(save_dir: str, tag: str, state, *,
                    client_state: Optional[Dict[str, Any]] = None,
                    config_dict: Optional[Dict[str, Any]] = None,
                    engine: Optional[CheckpointEngine] = None,
                    save_latest: bool = True, write_integrity: bool = True,
                    checksums: bool = True, keep_last_k: int = 0) -> str:
    """DeepSpeed directory contract: save_dir/tag/{state,meta.json}; plus
    save_dir/latest containing the tag, a content manifest, and an atomic
    COMMITTED marker written last (robustness/integrity.py)."""
    engine = engine or OrbaxCheckpointEngine()
    ckpt_path = os.path.join(save_dir, str(tag))
    os.makedirs(save_dir, exist_ok=True)
    rb_faults.io_seam("ckpt_save", ckpt_path)  # whole-save abort seam
    if os.path.isdir(ckpt_path):
        # overwriting a tag in place: drop its commit marker first so a
        # crash mid-overwrite reads as torn, never as the OLD save's
        # marker vouching for MIXED content. When THIS save won't write a
        # manifest, drop the stale one too — otherwise the finished save
        # would validate as uncommitted forever.
        integrity.invalidate(ckpt_path, drop_manifest=not write_integrity)

    def finalize():
        # runs only after the state dir is durable (possibly async)
        meta = {
            "tag": str(tag),
            "client_state": client_state or {},
            "config": config_dict or {},
            "world_size": jax.device_count(),
            "framework_version": "deepspeed_tpu-0.1",
        }
        _write_small(os.path.join(ckpt_path, "meta.json"),
                     json.dumps(meta, indent=2, default=str),
                     "checkpoint meta write")
        finalize_tag(save_dir, tag, save_latest=save_latest,
                     write_integrity=write_integrity, checksums=checksums,
                     keep_last_k=keep_last_k)

    engine.save(state, os.path.join(ckpt_path, "state"), on_complete=finalize)
    return ckpt_path


def resolve_load_tag(load_dir: str, tag: Optional[str] = None, *,
                     exclude: Iterable[str] = (),
                     deep: bool = True) -> Tuple[str, bool]:
    """Resolve which tag to load. Returns (tag, fell_back).

    An explicit tag is honored verbatim (the caller asked for exactly that
    save). tag=None resolves `latest`, validates it against the integrity
    chain, and on a torn/corrupt/uncommitted/missing target walks back to
    the newest tag that still validates — emitting a ``ckpt_fallback``
    event — instead of raising. Raises FileNotFoundError only when nothing
    under load_dir is loadable."""
    if tag is not None:
        return str(tag), False
    latest = os.path.join(load_dir, LATEST_FILE)
    requested = None
    if os.path.exists(latest):
        with open(latest) as f:
            requested = f.read().strip()
    if requested is not None and requested not in set(exclude):
        ok, reason = integrity.validate_tag(
            os.path.join(load_dir, requested), deep=deep)
        if ok:
            return requested, False
    elif requested is None:
        reason = f"no '{LATEST_FILE}' file"
    else:
        reason = "load failed"
    fallback = integrity.newest_valid_tag(
        load_dir, exclude=set(exclude) | ({requested} if requested else set()),
        deep=deep)
    if fallback is None:
        raise FileNotFoundError(
            f"no valid checkpoint under {load_dir} "
            f"(latest={requested!r}: {reason})")
    logger.warning(f"checkpoint fallback: latest={requested!r} is not "
                   f"loadable ({reason}); falling back to newest valid "
                   f"tag '{fallback}'")
    rb_events.emit("ckpt_fallback", dir=load_dir, requested=requested,
                   resolved=fallback, reason=reason)
    return fallback, True


def load_checkpoint(load_dir: str, tag: Optional[str] = None, *,
                    template=None, shardings=None,
                    engine: Optional[CheckpointEngine] = None):
    """Returns (state, client_state). tag=None reads the `latest` file
    (reference: load_checkpoint:2512 latest resolution), validates it
    against the integrity chain, and walks back to the newest valid tag
    when `latest` points at a torn/corrupt/uncommitted save."""
    engine = engine or OrbaxCheckpointEngine()

    def load_one(t: str):
        ckpt_path = os.path.join(load_dir, str(t))
        state = engine.load(os.path.join(ckpt_path, "state"), template,
                            shardings)
        meta_path = os.path.join(ckpt_path, "meta.json")
        client_state = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                client_state = json.load(f).get("client_state", {})
        logger.info(f"loaded checkpoint {ckpt_path}")
        return state, client_state

    if tag is not None:
        return load_one(tag)
    # tag=None: resolve + validate; if a validated tag STILL fails to load
    # (validation was shallow, or the payload format itself is bad) keep
    # walking back rather than bricking the resume path
    tried = set()
    last_err = None
    while True:
        try:
            resolved, _fell_back = resolve_load_tag(load_dir, None,
                                                    exclude=tried)
        except FileNotFoundError:
            if last_err is not None:
                raise last_err
            raise
        try:
            return load_one(resolved)
        except Exception as e:  # noqa: BLE001 - any load failure walks back
            tried.add(resolved)
            last_err = e
            logger.warning(f"checkpoint tag '{resolved}' validated but "
                           f"failed to load ({e!r}); walking back")
            rb_events.emit("ckpt_fallback", dir=load_dir, requested=resolved,
                           resolved=None, reason=f"load-error: {e}")
