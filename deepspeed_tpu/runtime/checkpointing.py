"""Sharded checkpoint save/load.

Reference: ``runtime/engine.py`` ``save_checkpoint:2817`` / ``load_checkpoint:
2512`` (per-rank ZeRO shards, `latest` tag file, tag validation,
client_state), pluggable ``CheckpointEngine`` (``runtime/checkpoint_engine/``),
and the offline universal-checkpoint tooling (``deepspeed/checkpoint/``,
``utils/zero_to_fp32.py``).

TPU-native: Orbax/TensorStore writes each array sharded and restores it under
*any* mesh — so elastic resume and "universal checkpoint" are by-construction
(SURVEY §5: "Orbax sharded async checkpoint with logical-axis metadata =
universal checkpoint by construction"). The DeepSpeed directory contract is
preserved: <dir>/<tag>/..., a `latest` file, and a client_state payload.
"""

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger

LATEST_FILE = "latest"


class CheckpointEngine:
    """Base checkpoint engine (reference: checkpoint_engine.py:6). The Orbax
    engine below is the default; TorchCheckpointEngine's role (one file per
    rank) has no TPU equivalent — sharding lives inside TensorStore."""

    def save(self, state, path: str):
        raise NotImplementedError

    def load(self, path: str, template=None, shardings=None):
        raise NotImplementedError

    def commit(self, tag: str):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    def __init__(self, async_save: bool = False):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.async_save = async_save
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler()) \
            if async_save else ocp.StandardCheckpointer()

    def save(self, state, path: str):
        path = os.path.abspath(path)
        if os.path.exists(path):
            shutil.rmtree(path)
        self._ckptr.save(path, state)
        if not self.async_save:
            self.wait()

    def wait(self):
        try:
            self._ckptr.wait_until_finished()
        except AttributeError:
            pass

    def load(self, path: str, template=None, shardings=None):
        path = os.path.abspath(path)
        if template is not None and shardings is not None:
            abstract = jax.tree.map(
                lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s),
                template, shardings)
            return self._ckptr.restore(path, abstract)
        if template is not None:
            abstract = jax.tree.map(
                lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), template)
            return self._ckptr.restore(path, abstract)
        return self._ckptr.restore(path)


def save_checkpoint(save_dir: str, tag: str, state, *,
                    client_state: Optional[Dict[str, Any]] = None,
                    config_dict: Optional[Dict[str, Any]] = None,
                    engine: Optional[CheckpointEngine] = None,
                    save_latest: bool = True) -> str:
    """DeepSpeed directory contract: save_dir/tag/{state,meta.json}; plus
    save_dir/latest containing the tag."""
    engine = engine or OrbaxCheckpointEngine()
    ckpt_path = os.path.join(save_dir, str(tag))
    os.makedirs(save_dir, exist_ok=True)
    engine.save(state, os.path.join(ckpt_path, "state"))
    meta = {
        "tag": str(tag),
        "client_state": client_state or {},
        "config": config_dict or {},
        "world_size": jax.device_count(),
        "framework_version": "deepspeed_tpu-0.1",
    }
    with open(os.path.join(ckpt_path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    if save_latest:
        with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
            f.write(str(tag))
    logger.info(f"saved checkpoint {ckpt_path}")
    return ckpt_path


def load_checkpoint(load_dir: str, tag: Optional[str] = None, *,
                    template=None, shardings=None,
                    engine: Optional[CheckpointEngine] = None):
    """Returns (state, client_state). tag=None reads the `latest` file
    (reference: load_checkpoint:2512 latest resolution)."""
    engine = engine or OrbaxCheckpointEngine()
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            raise FileNotFoundError(f"no '{LATEST_FILE}' file under {load_dir}")
        with open(latest) as f:
            tag = f.read().strip()
    ckpt_path = os.path.join(load_dir, str(tag))
    state = engine.load(os.path.join(ckpt_path, "state"), template, shardings)
    meta_path = os.path.join(ckpt_path, "meta.json")
    client_state = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            client_state = json.load(f).get("client_state", {})
    logger.info(f"loaded checkpoint {ckpt_path}")
    return state, client_state
