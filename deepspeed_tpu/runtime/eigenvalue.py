"""Hessian eigenvalue estimation (power iteration).

Reference: ``deepspeed/runtime/eigenvalue.py`` (Eigenvalue — per-block power
iteration over the loss Hessian using autograd double-backward; feeds MoQ's
quantization-period scheduling).

TPU-native: the Hessian-vector product is one `jax.jvp`-of-`jax.grad`
composition (no retained graphs or manual zero_grad), jitted once and
iterated; per-block estimates come from restricting the probe vector to one
top-level subtree at a time.
"""

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class Eigenvalue:
    """Power-iteration max-|eigenvalue| of the loss Hessian.

    verbose/tol/max_iterations mirror the reference's constructor surface.
    """

    def __init__(self, verbose: bool = False, max_iterations: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1):
        self.verbose = verbose
        self.max_iterations = max_iterations
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution

    def _hvp_fn(self, loss_fn: Callable):
        def hvp(params, v):
            return jax.jvp(jax.grad(loss_fn), (params,), (v,))[1]
        return jax.jit(hvp)

    def compute_eigenvalue(self, loss_fn: Callable, params,
                           rng: Optional[jax.Array] = None) -> float:
        """Top |eigenvalue| of d2(loss)/dparams2 via power iteration."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        hvp = self._hvp_fn(loss_fn)
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(rng, len(leaves))
        # probe must match the param dtypes (jvp rejects mismatched tangents
        # — bf16 params are the norm here); norms/vdots still accumulate f32
        v = jax.tree.unflatten(treedef, [
            jax.random.normal(k, l.shape, l.dtype)
            for k, l in zip(keys, leaves)])

        def norm(t):
            return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                                for x in jax.tree.leaves(t))).astype(
                jax.tree.leaves(t)[0].dtype)

        ev = 0.0
        for i in range(self.max_iterations):
            n = norm(v)
            v = jax.tree.map(lambda x: x / (n + self.stability), v)
            hv = hvp(params, v)
            new_ev = float(sum(
                jnp.vdot(a.astype(jnp.float32), b.astype(jnp.float32))
                for a, b in zip(jax.tree.leaves(v), jax.tree.leaves(hv))))
            if self.verbose:
                logger.info(f"eigenvalue iter {i}: {new_ev:.6f}")
            if i > 0 and abs(new_ev - ev) <= self.tol * max(abs(new_ev), 1e-12):
                ev = new_ev
                break
            ev = new_ev
            v = hv
        return abs(ev)

    def compute_blockwise(self, loss_fn: Callable, params,
                          rng: Optional[jax.Array] = None
                          ) -> Dict[str, float]:
        """Per-top-level-subtree eigenvalues (reference: per-layer blocks)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        out = {}
        for i, key in enumerate(params):
            sub_rng = jax.random.fold_in(rng, i)

            def block_loss(block, key=key):
                merged = dict(params)
                merged[key] = block
                return loss_fn(merged)

            out[str(key)] = self.compute_eigenvalue(block_loss, params[key],
                                                    sub_rng)
        return out
