"""The training engine.

Reference: ``deepspeed/runtime/engine.py:183`` (DeepSpeedEngine) and
``deepspeed/__init__.py:52`` (initialize). The reference engine is a hook
machine: it wraps an eager nn.Module, intercepts forward/backward, buckets
grads, and drives partitioned optimizers. Here the engine is a *compiler
front-end*: it resolves config -> mesh plan -> sharding specs, builds ONE
jitted train_step (forward + backward + grad-accum + optimizer + loss-scale
update, with buffer donation), and XLA performs what stage_1_and_2.py /
stage3.py do by hand (reduce-scatter of grads, partitioned optimizer step,
all-gather of updated params, overlap of comm with compute).

API parity:
  initialize(...) -> (engine, optimizer, dataloader, lr_scheduler)
  engine.train_batch(batch)            — pipe-engine-style one-call step
  engine.forward / backward / step     — eager-style 3-call loop (grad
                                          accumulation across calls, like the
                                          reference's micro-batch loop)
  engine.save_checkpoint / load_checkpoint
  engine.global_steps, get_lr, get_loss_scale, ...
"""

import contextlib
import dataclasses
import json
import math
import os
import time
from functools import partial
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.config import Config
from deepspeed_tpu.ops.registry import get_optimizer_builder
from deepspeed_tpu.ops.optimizers import Optimizer, global_grad_norm
from deepspeed_tpu.parallel import (
    MeshPlan, build_mesh, make_rules, plan_from_config, spec_tree, num_params)
from deepspeed_tpu.runtime import fp16 as fp16_mod
from deepspeed_tpu.runtime import zero as zero_mod
from deepspeed_tpu.runtime import checkpointing as ckpt_mod
from deepspeed_tpu.runtime.lr_schedules import get_scheduler
from deepspeed_tpu.telemetry import accumulators as tel_acc
from deepspeed_tpu.utils import logging as log_mod
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

logger = log_mod.logger

# optimizers the flat-chunk swap kernels implement (reference: the cpu-adam
# restriction on the swap_tensor path)
_ADAM_FAMILY = ("adam", "adamw", "cpuadam", "fusedadam")


def _opt_name(config) -> str:
    return (config.optimizer.name if config.optimizer else "adamw").lower()



def initialize(args=None, model=None, config=None, config_params=None,
               optimizer=None, lr_scheduler=None, mesh=None, rng=None,
               model_parameters=None, dist_init_required=None, mpu=None,
               **kwargs):
    """Build an Engine (reference: ``deepspeed/__init__.py:52``).

    `model` is a ModelSpec (deepspeed_tpu.models) or any object with
    .init/.loss_fn/.logical_axes. Returns (engine, optimizer, dataloader,
    lr_scheduler) for signature parity — dataloader is None unless
    training_data is passed via kwargs.
    """
    cfg = Config.load(config if config is not None else config_params)
    if args is not None and getattr(args, "deepspeed_config", None):
        cfg = Config.load(args.deepspeed_config)
    if cfg.autotuning.enabled:
        # reference: autotuning/autotuner.py:39 — search mesh/zero/microbatch/
        # remat before building the real engine, then build with the winner
        from deepspeed_tpu.autotuning import autotune_config
        src = config if config is not None else config_params
        if src is None and args is not None:
            src = getattr(args, "deepspeed_config", None)
        if isinstance(src, dict):
            raw = json.loads(json.dumps(src))
        else:
            with open(src) as f:
                raw = json.load(f)
        raw, model = autotune_config(model, raw,
                                     devices=kwargs.get("devices"))
        cfg = Config.load(raw)
    if cfg.elasticity.enabled:
        # reference: elasticity/elasticity.py:231 — pin a batch size
        # compatible with the widest device-count range, then derive the
        # micro/gas split for THIS world size
        from deepspeed_tpu.elasticity import compute_elastic_config
        devs = kwargs.get("devices")
        ws = len(devs) if devs else jax.device_count()
        if not cfg.elasticity.ignore_non_elastic_batch_info and any(
                v is not None for v in (cfg.train_batch_size,
                                        cfg.train_micro_batch_size_per_gpu,
                                        cfg.gradient_accumulation_steps)):
            raise ValueError(
                "elasticity sets the batch triad itself; remove "
                "train_batch_size/train_micro_batch_size_per_gpu/"
                "gradient_accumulation_steps or set "
                "ignore_non_elastic_batch_info")
        # the batch triad is per DATA-parallel replica, not per chip: a
        # tensor/pipe-parallel mesh divides the chips among model shards
        dp = plan_from_config(cfg, ws).dp_world_size
        fb, _valid, micro = compute_elastic_config(
            dataclasses.asdict(cfg.elasticity), world_size=dp)
        cfg.train_batch_size = fb
        cfg.train_micro_batch_size_per_gpu = micro
        cfg.gradient_accumulation_steps = fb // (micro * dp)
    engine = Engine(model=model, config=cfg, optimizer=optimizer,
                    lr_scheduler=lr_scheduler, mesh=mesh, rng=rng,
                    devices=kwargs.get("devices"))
    training_data = kwargs.get("training_data")
    dataloader = None
    if training_data is not None:
        from deepspeed_tpu.runtime.dataloader import DataLoader
        # train_batch() consumes GLOBAL batches (train_batch_size rows)
        dataloader = DataLoader(training_data,
                                batch_size=engine.config.train_batch_size)
        # checkpoints carry the loader's position (epoch/batch/seed) so an
        # elastic resume neither replays nor skips data
        engine.attach_dataloader(dataloader)
    return engine, engine.optimizer, dataloader, engine.lr_scheduler


class Engine:
    def __init__(self, model, config: Config, optimizer: Optional[Optimizer] = None,
                 lr_scheduler=None, mesh: Optional[Mesh] = None, rng=None,
                 devices=None):
        from deepspeed_tpu import comm
        comm.init_distributed()

        self.model = model
        self.config = config
        self.accelerator = get_accelerator()

        # --- mesh plan (reference: _configure_distributed_model:1052 + groups)
        n_devices = len(devices) if devices is not None else jax.device_count()
        self.plan: MeshPlan = plan_from_config(config, n_devices)
        self.mesh: Mesh = mesh if mesh is not None else build_mesh(self.plan, devices)
        from deepspeed_tpu.parallel.context import set_parallel_context
        set_parallel_context(self.mesh, self.plan)
        # ZeRO-Infinity layer streaming: with an explicit mesh it composes
        # with data/fsdp parallelism (batch triad resolves against the full
        # dp degree); with no mesh config it stays the legacy single-device
        # capacity executor regardless of the harness's device count
        self._infinity_multi = (_infinity_mode(config)
                                and bool(config.mesh.axes)
                                and self.plan.world_size > 1)
        config.resolve_batch_size(
            self.plan.dp_world_size
            if (not _infinity_mode(config) or self._infinity_multi) else 1)
        logger.info(zero_mod.describe(config.zero_optimization, self.plan))
        logger.info(f"batch: train={config.train_batch_size} "
                    f"micro={config.train_micro_batch_size_per_gpu} "
                    f"gas={config.gradient_accumulation_steps} "
                    f"dp={self.plan.dp_world_size}")
        if config.sparse_gradients:
            # reference: engine.py:2302-2369 sparse_allreduce_list. N/A by
            # design here — see sparse_gradients_enabled() and
            # benchmarks/embedding_grad.py for the byte math
            logger.warning(
                "sparse_gradients=true is a no-op on TPU: embedding "
                "cotangents are fused scatter-adds reduce-scattered over "
                "ICI with the other grads (V*H/dp bytes/chip); a "
                "(values, indices) wire would need dynamic shapes and "
                "moves more bytes at realistic vocab/batch sizes")

        # --- model-level perf levers (`transformer` config section):
        # applied with the act-quant rebuild idiom — dataclasses.replace +
        # make_model keeps the param structure identical; only the compute
        # path (fused attention backward, chunked TP collective overlap)
        # changes. Runs BEFORE pipeline wrapping so staged models get the
        # same levers.
        tcfg = config.transformer
        if tcfg.fused_backward or tcfg.tp_overlap_chunks > 1:
            from deepspeed_tpu.models.transformer import (
                TransformerConfig as _TC)
            if isinstance(getattr(model, "config", None), _TC):
                from deepspeed_tpu.models import make_model as _mk
                model = _mk(dataclasses.replace(
                    model.config, fused_backward=tcfg.fused_backward,
                    tp_overlap_chunks=int(tcfg.tp_overlap_chunks)),
                    name=model.name)
                self.model = model
                logger.info(
                    "transformer tuning: fused_backward="
                    f"{tcfg.fused_backward} tp_overlap_chunks="
                    f"{tcfg.tp_overlap_chunks}")
            else:
                logger.warning("`transformer` config section ignored: model "
                               "is not a transformer ModelSpec")

        # --- pipeline wrapping (reference: PipelineEngine construction)
        self._pp_mode = self.plan.pipe > 1
        if self._pp_mode and self.plan.seq > 1:
            raise ValueError("pipe>1 with seq>1 is not supported: ring "
                             "attention cannot nest inside the pipelined "
                             "manual mesh region")
        if self._pp_mode:
            from deepspeed_tpu.models.transformer import TransformerConfig
            from deepspeed_tpu.models.pipeline_wrapper import make_pipelined_model
            if not isinstance(getattr(model, "config", None), TransformerConfig):
                raise ValueError("pipeline parallelism requires a transformer "
                                 "ModelSpec (stacked-layer params)")
            model = make_pipelined_model(
                model.config, self.mesh,
                num_microbatches=config.gradient_accumulation_steps,
                name=f"{model.name}-pp{self.plan.pipe}")
            self.model = model
            logger.info(f"pipeline mode: {self.plan.pipe} stages, "
                        f"{config.gradient_accumulation_steps} microbatches")

        # --- sharding rules
        zero_cfg = config.zero_optimization
        self.rules = make_rules(zero_cfg.stage, tp=self.plan.tensor > 1,
                                pipe=self._pp_mode)
        laxes = model.logical_axes
        base_specs = spec_tree(laxes, self.rules)
        # shapes via eval_shape (no memory)
        self._rng = rng if rng is not None else jax.random.PRNGKey(config.seed)
        param_shapes = jax.eval_shape(model.init, self._rng)
        shape_tree = jax.tree.map(lambda s: s.shape, param_shapes)
        self._shape_tree = shape_tree  # comm.schedule needs divisibility info
        self.param_specs = jax.tree.map(
            lambda spec, sh: zero_mod.zero_param_spec(spec, sh, self.plan, zero_cfg),
            base_specs, shape_tree, is_leaf=lambda x: isinstance(x, P))
        self.grad_specs = zero_mod.tree_grad_spec(
            self.param_specs, shape_tree, self.plan, zero_cfg)
        self.opt_specs = zero_mod.tree_opt_spec(
            self.param_specs, shape_tree, self.plan, zero_cfg)
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self.param_specs,
            is_leaf=lambda x: isinstance(x, P))

        # --- precision (reference: _configure_distributed_model dtype + fp16 wrap)
        self.compute_dtype = config.compute_dtype
        self._fp16 = config.fp16.enabled
        use_master = self.compute_dtype != jnp.float32

        # --- optimizer-state offload (ZeRO-Offload / ZeRO-Infinity; reference:
        # runtime/zero/offload_config.py + swap_tensor/*). device=cpu keeps
        # states in pinned host DRAM; device=nvme streams fp32 state through
        # HBM from NVMe chunk files (swap_tensor.NVMeOptimizerSwapper).
        off_opt_cfg = config.zero_optimization.offload_optimizer
        self._nvme_opt = off_opt_cfg.enabled and off_opt_cfg.device == "nvme"
        self._offload_opt = off_opt_cfg.enabled and off_opt_cfg.device == "cpu"
        self._swapper = None
        if self._nvme_opt and not _infinity_mode(config):
            if not off_opt_cfg.nvme_path:
                raise ValueError("offload_optimizer.device=nvme requires "
                                 "offload_optimizer.nvme_path")
            if _opt_name(config) not in _ADAM_FAMILY:
                raise ValueError(
                    f"offload_optimizer.device=nvme supports the Adam family "
                    f"only (got '{_opt_name(config)}') — the flat-chunk swap "
                    f"kernel is Adam; reference has the same restriction")
            if optimizer is not None:
                raise ValueError("offload_optimizer.device=nvme requires a "
                                 "config-built optimizer, not a client one")
        self._swap_storage = "nvme"
        if self._offload_opt and not _infinity_mode(config):
            kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
            has_pinned = "pinned_host" in kinds
            on_cpu = get_accelerator().platform == "cpu"
            if off_opt_cfg.use_cpu_adam:
                if (_opt_name(config) not in _ADAM_FAMILY
                        and _opt_name(config) != "adagrad") or \
                        optimizer is not None:
                    # same contract as the nvme swapper: the fused host
                    # kernels cover the Adam family + Adagrad (reference:
                    # csrc/{adam,adagrad}/cpu_*.cpp), config-built only
                    raise ValueError(
                        "offload_optimizer.use_cpu_adam requires a config-"
                        f"built Adam-family or Adagrad optimizer (got "
                        f"'{_opt_name(config)}'"
                        f"{', client-supplied' if optimizer else ''})")
                if _opt_name(config) == "adagrad":
                    from deepspeed_tpu.ops.cpu_adagrad import (
                        cpu_adagrad_available as cpu_adam_available)
                else:
                    from deepspeed_tpu.ops.cpu_adam import cpu_adam_available
                if cpu_adam_available():
                    # the optimizer runs ON the host (native fused CPU-Adam)
                    # over host-resident fp32 state: 4 bytes/param/step on
                    # the bus instead of 28 (reference: DeepSpeedCPUAdam)
                    self._nvme_opt = True
                    self._offload_opt = False
                    self._swap_storage = "cpu_adam"
                    logger.info("optimizer state offload: host CPU-Adam "
                                "(fp32 state host-resident)")
                else:
                    logger.warning("use_cpu_adam requested but the native "
                                   "library failed to build; falling back "
                                   "to the chunk-streamed tier")
            if self._swap_storage == "cpu_adam":
                pass  # routed above
            elif _opt_name(config) in _ADAM_FAMILY and optimizer is None:
                # device=cpu rides the same chunked double-buffered swapper
                # as NVMe, with host-tier buffers instead of files — the
                # round trip streams per chunk and overlaps with compute
                # (round-2 verdict: the old path moved the WHOLE opt tree
                # to device and back eagerly every step)
                self._nvme_opt = True
                self._offload_opt = False
                self._swap_storage = "host" if (on_cpu or not has_pinned) \
                    else "pinned"
                logger.info("optimizer state offload: chunk-streamed "
                            f"{self._swap_storage} tier (pipelined swapper)")
            elif not has_pinned:
                # the eager fallback needs real pinned_host memory
                logger.warning("offload_optimizer requested but pinned_host "
                               "memory unavailable; disabling")
                self._offload_opt = False
            else:
                logger.info("optimizer state offload: pinned_host DRAM "
                            "(eager round-trip; non-Adam or client "
                            "optimizer cannot use the flat-chunk swapper)")

        # --- param offload (ZeRO-Infinity param path; reference:
        # swap_tensor/partitioned_param_swapper.py). Stacked layer weights
        # live in pinned host DRAM; the forward scan streams one layer at a
        # time into HBM (models/transformer.py body device_put).
        off_p_cfg = config.zero_optimization.offload_param
        # ZeRO-Infinity layer-streamed executor: owns BOTH the param chunks
        # and the optimizer chunks (reference: partitioned_param_swapper.py:35
        # + stage3.py:1735 sub-group loop). Two tiers:
        #   device=nvme          -> AIO chunk files (local-NVMe deployments)
        #   device=cpu (+opt cpu)-> TPU-host pinned DRAM (ZeRO-Offload tier)
        self._infinity = _infinity_mode(config)
        self._infinity_exec = None
        self._infinity_backend = None
        if self._infinity:
            if off_p_cfg.device == "nvme" or off_opt_cfg.device == "nvme":
                # the LayerStore is one tier for param AND opt chunks: a
                # mixed cpu/nvme request collapses to nvme as the system of
                # record — the executor's full host bf16-bits param cache
                # (offload_param.max_in_cpu, default all layers) gives the
                # cpu-tier refetch speed on top
                self._infinity_backend = "nvme"
                if off_p_cfg.device == "cpu":
                    logger.info(
                        "offload_param.device=cpu + offload_optimizer."
                        "device=nvme: chunks persist on nvme; the host "
                        "param cache keeps params cpu-resident for refetch")
            elif get_accelerator().platform == "cpu":
                self._infinity_backend = "host"  # CPU tests: plain buffers
            else:
                self._infinity_backend = "pinned"
            if not off_opt_cfg.enabled:
                # reference ZeRO-3 can offload params while keeping the
                # optimizer in HBM; the layer-streamed executor owns both —
                # opt chunks ride the same tier as the params
                logger.info("offload_param without offload_optimizer: "
                            "optimizer chunks ride the param tier (the "
                            "executor streams both per layer)")
            from deepspeed_tpu.models.transformer import TransformerConfig
            if not isinstance(getattr(model, "config", None), TransformerConfig):
                raise ValueError("offload_param requires a transformer "
                                 "ModelSpec (layer streaming)")
            if self._infinity_backend == "nvme":
                if not (off_p_cfg.nvme_path or off_opt_cfg.nvme_path):
                    raise ValueError("offload_param.device=nvme requires "
                                     "nvme_path")
                if off_opt_cfg.enabled and off_opt_cfg.device == "cpu" \
                        and off_p_cfg.device == "nvme":
                    logger.info(
                        "offload_param.device=nvme + offload_optimizer."
                        "device=cpu: opt chunks persist on nvme with the "
                        "params (one LayerStore tier)")
            if self._infinity_multi:
                # offload composed with data/fsdp/tensor parallelism
                # (reference: ZeRO-3 + NVMe under a Megatron TP mpu,
                # engine.py:1088-1100 + stage3.py:65): layer chunks shard
                # over fsdp x tensor, batch over (data, fsdp), and the
                # per-layer jits re-shard the unflattened weights to
                # Megatron col/row specs
                if (self.plan.pipe > 1 or self.plan.seq > 1
                        or self.plan.expert > 1):
                    raise ValueError(
                        "layer-streamed offload shards over "
                        "data/fsdp/tensor (pipe/seq/expert must be 1)")
            elif self.plan.world_size > 1:
                if get_accelerator().platform == "cpu":
                    # CPU test harness: single-device executor is fine
                    logger.warning(
                        "the layer-streamed executor runs single-device "
                        "without an explicit mesh config; set mesh.axes "
                        "{data/fsdp} to shard it")
                else:
                    # on real multi-chip hardware silently training on one
                    # chip (with 7 idle) is never what the user configured
                    raise ValueError(
                        "multi-device layer-streamed offload requires an "
                        "explicit mesh config: set mesh.axes {'data': N} "
                        "and/or {'fsdp': N}")
            if self._pp_mode:
                raise ValueError("layer-streamed offload with pipeline "
                                 "parallelism is not supported")
            # fp16 composes: the executor carries host-side dynamic loss
            # scaling (storage bits stay bf16; the fp32 master in the opt
            # chunks carries precision)
            if _opt_name(config) not in ("adam", "adamw"):
                raise ValueError("layer-streamed offload supports the "
                                 f"Adam family only (got "
                                 f"'{_opt_name(config)}')")
            if optimizer is not None:
                raise ValueError("layer-streamed offload requires a "
                                 "config-built optimizer, not a client one")
            # the executor replaces the swapper AND the jitted train step
            self._nvme_opt = False
        # every offload_param configuration routes through the layer-streamed
        # executor above (round-5: the old non-streamed scan-fetch train path
        # was single-device-only — an in-graph host writeback this runtime
        # rejects — and is deleted; cfg.offload_params scan-fetch remains for
        # INFERENCE capacity, models/transformer.py:1089)

        # --- optimizer (reference: _configure_optimizer:1175)
        self.lr_scheduler = lr_scheduler
        self._schedule = None
        if lr_scheduler is None and config.scheduler is not None:
            self._schedule = get_scheduler(config.scheduler.name,
                                           config.scheduler.params)
            self.lr_scheduler = self._schedule
        elif callable(lr_scheduler):
            self._schedule = lr_scheduler
        if optimizer is not None:
            from deepspeed_tpu.ops.optimizers import from_optax, is_optax_transform
            self.optimizer = from_optax(optimizer) if is_optax_transform(optimizer) \
                else optimizer
        else:
            opt_cfg = config.optimizer
            name = opt_cfg.name if opt_cfg else "adamw"
            params = dict(opt_cfg.params) if opt_cfg else {}
            if self._schedule is not None:
                params["lr"] = self._schedule
            params.setdefault("use_master_weights", use_master)
            builder = get_optimizer_builder(name)
            self.optimizer = builder(**params)
        self._base_lr = None
        if config.optimizer and "lr" in config.optimizer.params:
            self._base_lr = config.optimizer.params["lr"]

        # --- 1-bit compressed communication path (reference: the NCCL/MPI
        # compressed_allreduce backends, runtime/comm/nccl.py:53). Grads stay
        # per-device local inside a shard_map over `data`; only packed sign
        # bits cross the wire in the compressed phase.
        from deepspeed_tpu.ops.onebit import PhasedOptimizer
        self._onebit_comm = False
        if isinstance(self.optimizer, PhasedOptimizer) and self.plan.data > 1:
            pure_dp = (self.plan.tensor == 1 and self.plan.pipe == 1
                       and self.plan.fsdp == 1 and self.plan.expert == 1
                       and self.plan.seq == 1)
            # ZeRO stays off by design: the 1-bit algorithm keeps FULL
            # momentum + master per rank (local momentum accumulates the
            # full local gradient before compression), so optimizer-state
            # sharding cannot compose — the reference's 1-bit optimizers
            # carry the same ZeRO restriction.
            ok = (pure_dp and zero_cfg.stage == 0
                  and not self._offload_opt and not self._nvme_opt)
            if ok:
                self._onebit_comm = True
                extras = []
                if self._fp16:
                    extras.append("fp16 loss scaling in-step")
                if config.gradient_clipping:
                    extras.append("synchronized norm-proxy clipping")
                logger.info("1-bit optimizer: compressed communication over "
                            f"data axis ({self.plan.data} ranks), packed "
                            "sign all-gather in the compressed phase"
                            + (f" ({', '.join(extras)})" if extras else ""))
            else:
                logger.warning(
                    "1-bit optimizer: compressed communication requires a "
                    "pure data-parallel mesh, zero stage 0, and no "
                    "offload — falling back to dense (error-feedback "
                    "sign update semantics are preserved, bytes are not "
                    "reduced)")

        # --- compression (reference: compression/compress.py:92) — a traced
        # param transform inside the step; masters stay full precision
        self._compression = None
        comp_cfg = dataclasses.asdict(config.compression_training)
        if any(((comp_cfg.get(k) or {}).get("shared_parameters", {})
                .get("enabled") or (comp_cfg.get(k) or {}).get("enabled"))
               for k in ("weight_quantization", "sparse_pruning",
                         "row_pruning", "head_pruning",
                         "activation_quantization", "channel_pruning",
                         "layer_reduction")):
            from deepspeed_tpu.compression import init_compression
            self._compression = init_compression(comp_cfg)
            # composes with the 1-bit compressed-comm path: the shard_map
            # step applies the same traced param transform inside its
            # per-device loss (see _get_onebit_step)
            # activation quantization / layer reduction reshape the MODEL,
            # not the params (reference: QuantAct wraps forward;
            # student_initialization builds a shallower net)
            self._act_quant = self._compression.activation_quant
            self._act_quant_on = False
            lr = self._compression.layer_reduction
            if self._act_quant or lr:
                from deepspeed_tpu.models.transformer import TransformerConfig
                if not isinstance(getattr(model, "config", None),
                                  TransformerConfig):
                    raise ValueError("activation_quantization/layer_reduction "
                                     "require a transformer ModelSpec")
            if lr is not None:
                import dataclasses as _dc
                from deepspeed_tpu.models import make_model as _mk
                keep = lr["keep_number"]
                model = _mk(_dc.replace(model.config, num_layers=keep),
                            name=f"{model.name}-student{keep}")
                self.model = model
                logger.info(f"layer reduction: student keeps {keep} layers")
                if lr["teacher_layer"]:
                    # the engine has no teacher weights to copy from —
                    # teacher init is an explicit user step, as in the
                    # reference's student_initialization utility
                    logger.warning(
                        "layer_reduction.teacher_layer is informational "
                        "here: initialize the student from a trained "
                        "teacher with compression.student_params_from_"
                        "teacher(...) and assign engine.state['params']")
            if self._act_quant and self._act_quant[1] <= 0:
                # no schedule offset: bake quantized activations in now
                model = self._rebuild_act_quant(model)
        else:
            self._act_quant = None
            self._act_quant_on = False

        # --- MoQ (reference: runtime/quantize.py + engine eigenvalue
        # events): eigenvalue-scheduled quantization of the layer stack
        from deepspeed_tpu.runtime.quantize import build_moq
        self._moq = None
        if config.quantize_training.get("enabled"):
            from deepspeed_tpu.models.transformer import TransformerConfig
            if not isinstance(getattr(model, "config", None),
                              TransformerConfig):
                raise ValueError("quantize_training (MoQ) requires a "
                                 "transformer ModelSpec (stacked layers)")
            if self._pp_mode:
                raise ValueError("quantize_training (MoQ) with pipeline "
                                 "parallelism is not supported")
            if _infinity_mode(config) and \
                    (config.quantize_training.get("eigenvalue") or {}) \
                    .get("enabled"):
                # the blockwise-Rayleigh curvature probe needs the resident
                # stacked-layer tree; streamed layers fall back to the
                # uniform quantize_period schedule
                logger.warning(
                    "MoQ eigenvalue scheduling requires resident params; "
                    "layer-streamed offload uses the uniform "
                    "quantize_period for every layer")
            # composes with the 1-bit compressed-comm path: the shard_map
            # step applies the same traced _moq_bits transform inside its
            # per-device loss (see _get_onebit_step)
            self._moq = build_moq(config.quantize_training,
                                  model.config.num_layers)

        # --- telemetry (deepspeed_tpu/telemetry): the accumulator leaf lives
        # in the donated jitted state so the jitted paths advance it in-graph;
        # host-driven optimizer paths (NVMe swapper, layer-streamed executor)
        # mirror it host-side — their metrics are host-resident by design
        tcfg = config.telemetry
        self._tel_cfg = tcfg if tcfg.enabled else None
        self._tel_in_graph = (tcfg.enabled and not self._nvme_opt
                              and not self._infinity)

        # --- state init (sharded at creation; reference: zero.Init equivalent)
        self.state_shardings = None
        if self._infinity:
            self.state = None  # streamed: the full tree never materializes
            self._infinity_exec = self._build_infinity()
        else:
            self.state = self._init_state()
            # --- jitted step functions
            self._compile_steps()

        # --- bookkeeping (reference: engine timers/monitor wiring)
        self.global_steps = 0
        # host-side part of the skip counter: the jitted paths account
        # skips in-graph (state["skipped"]); host-driven paths (NVMe
        # swapper, layer-streamed executor) bump this offset directly
        self._skipped_offset = 0
        self._ckpt_engine = None  # persistent async checkpoint engine
        self._last_grad_norm = None
        self._last_log_window = 0
        self.micro_steps = 0
        # --- robustness (deepspeed_tpu/robustness): deterministic fault
        # injection armed from config; the injector is PROCESS-global so an
        # elastic rebuild mid-run keeps the schedule's counters
        self._dataloader = None  # attach_dataloader: data position in ckpts
        self.fault_injector = None
        if config.robustness.faults.enabled:
            from deepspeed_tpu.robustness import faults as rb_faults
            self.fault_injector = rb_faults.install_from_config(
                config.robustness.faults)
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size,
            steps_per_output=config.steps_per_print)
        self._grad_buffer = None  # for forward/backward/step API
        self._accum_count = 0
        self.monitor = self._build_monitor()
        self.losses = None
        # --- telemetry host-side pieces (tracer, anomaly, window bookkeeping)
        self._tracer = None
        self._anomaly = None
        self._tel_host = None
        self._tel_prev = None        # last drained cumulative snapshot
        self._tel_wall = None        # perf_counter at the last drain
        self._tel_wall_steps = 0     # global_steps at the last drain
        self._tel_last_window = None  # last drained window stats (host dict)
        self._tel_static = None      # cached static-join cost ({} = failed)
        self._tel_static_thread = None  # background lower/compile worker
        import threading
        self._tel_lock = threading.Lock()  # guards _tel_static (worker
        # thread publishes the compiled cost; boundary drains poll it)
        self._tel_abs = None         # (jitted fn, abstract args, divisor)
        if self._tel_cfg is not None:
            from deepspeed_tpu.telemetry import (AnomalyDetector, HostWindow,
                                                 StepTracer)
            self._tracer = StepTracer(trace_cfg=self._tel_cfg.trace,
                                      max_events=self._tel_cfg.max_trace_events)
            if self._tel_cfg.anomaly.enabled:
                self._anomaly = AnomalyDetector(self._tel_cfg.anomaly)
            if not self._tel_in_graph:
                self._tel_host = HostWindow(self._tel_cfg.gnorm_hist_buckets)
        # comms-logger wiring (reference: the comms_logger config section
        # configures the logger at engine init; its totals reach the monitor
        # as comm/* events at steps_per_print boundaries — see _log_step)
        if config.comms_logger.enabled:
            from deepspeed_tpu.comm import comms_logger
            comms_logger.configure(
                enabled=True, verbose=config.comms_logger.verbose,
                prof_ops=(() if config.comms_logger.prof_all
                          else config.comms_logger.prof_ops))
        # --- data efficiency (reference: runtime/data_pipeline/*)
        self._curriculum = None
        if config.curriculum_learning.enabled:
            from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler
            if config.curriculum_learning.curriculum_type != "seqlen":
                raise ValueError("curriculum_type must be 'seqlen' (the "
                                 "reference's only in-engine curriculum)")
            self._curriculum = CurriculumScheduler(dataclasses.asdict(
                config.curriculum_learning))
            logger.info("curriculum learning: seqlen "
                        f"{self._curriculum.min_difficulty} -> "
                        f"{self._curriculum.max_difficulty} over "
                        f"{self._curriculum.total_step} steps")
        # progressive layer drop (reference: runtime/progressive_layer_drop.py
        # ProgressiveLayerDrop — theta(t) = (1-theta)*exp(-gamma*t) + theta)
        self._pld = None
        if config.progressive_layer_drop.enabled:
            from deepspeed_tpu.models.transformer import TransformerConfig
            if not isinstance(getattr(model, "config", None), TransformerConfig):
                raise ValueError("progressive_layer_drop requires a "
                                 "transformer ModelSpec")
            if self._pp_mode:
                raise ValueError("progressive_layer_drop with pipeline "
                                 "parallelism is not supported")
            if not model.config.scan_layers:
                raise ValueError("progressive_layer_drop requires "
                                 "scan_layers=True (the drop cond lives in "
                                 "the layer scan)")
            if not model.config.progressive_layer_drop:
                import dataclasses as _dc
                from deepspeed_tpu.models import make_model as _mk
                model = _mk(_dc.replace(model.config,
                                        progressive_layer_drop=True),
                            name=model.name)
                self.model = model
            self._pld = (config.progressive_layer_drop.theta,
                         config.progressive_layer_drop.gamma)
            logger.info(f"progressive layer drop: theta_floor={self._pld[0]} "
                        f"gamma={self._pld[1]}")
        self._ltd = None
        self._ltd_keep = None
        routing = config.data_efficiency.data_routing or {}
        if config.data_efficiency.enabled and \
                routing.get("random_ltd", {}).get("enabled"):
            from deepspeed_tpu.runtime.data_pipeline import RandomLTDScheduler
            from deepspeed_tpu.models.transformer import TransformerConfig
            if not isinstance(getattr(model, "config", None), TransformerConfig):
                raise ValueError("random_ltd requires a transformer ModelSpec")
            if self._pp_mode:
                raise ValueError("random_ltd with pipeline parallelism is not "
                                 "supported")
            self._ltd = RandomLTDScheduler(routing)
            self._ltd_orig_scan = model.config.scan_layers
            logger.info(f"random-ltd: kept tokens "
                        f"{self._ltd.min_value} -> {self._ltd.max_value}")
        n = num_params(param_shapes)
        logger.info(f"engine ready: {model.name if hasattr(model, 'name') else 'model'} "
                    f"{n / 1e6:.1f}M params, dtype={self.compute_dtype.__name__}, "
                    f"mesh={self.plan.describe()}")

    # ------------------------------------------------------------------
    def _build_monitor(self):
        try:
            from deepspeed_tpu.monitor import MonitorMaster
            return MonitorMaster(self.config)
        except Exception as e:
            # a typo'd W&B/TB config must not silently disable monitoring
            logger.warning(f"monitor disabled — backend init failed: {e!r}")
            return None

    def _init_state(self):
        cfg = self.config
        zero_cfg = cfg.zero_optimization
        mesh = self.mesh

        param_sh = self.param_shardings

        def make_state(key):
            params32 = self.model.init(key)
            # nvme offload: fp32 state lives on NVMe chunks, never in HBM
            opt_state = None if self._nvme_opt else self.optimizer.init(params32)
            params = jax.tree.map(
                lambda p: p.astype(self.compute_dtype), params32)
            state = {"params": params, "opt": opt_state,
                     "step": jnp.zeros((), jnp.int32)}
            if self._fp16:
                if cfg.fp16.dynamic:
                    ls = fp16_mod.init_loss_scale(cfg.fp16.initial_scale_power,
                                                  hysteresis=cfg.fp16.hysteresis)
                else:
                    ls = fp16_mod.static_loss_scale(cfg.fp16.loss_scale)
                state["loss_scale"] = {"scale": ls.scale,
                                       "good_steps": ls.good_steps,
                                       "hysteresis": ls.hysteresis}
                # device-resident skip accounting: the jitted step advances
                # this on overflow so the host never fetches the overflow
                # flag in the hot loop (engine.skipped_steps reads it lazily)
                state["skipped"] = jnp.zeros((), jnp.int32)
            if self._tel_in_graph:
                # telemetry accumulators ride the donated state the same way:
                # advanced in-graph, drained by _log_step's one batched fetch
                state["telemetry"] = tel_acc.init_leaf(
                    cfg.telemetry.gnorm_hist_buckets)
            return state

        # Determine opt-state sharding by matching leaves against params:
        # per-param tensors (same shape as a param) use opt_specs; scalars replicate.
        state_shapes = jax.eval_shape(make_state, self._rng)
        self.state_shardings = self._state_shardings_from(state_shapes)
        init_fn = jax.jit(make_state, out_shardings=self.state_shardings)
        with self.mesh:
            state = init_fn(self._rng)
        if self._onebit_comm:
            state = self._expand_rank_varying(state)
        if self._offload_opt:
            state["opt"] = self._opt_to_host(state["opt"])
        if self._nvme_opt:
            self._swapper = self._build_swapper(state_shapes["params"])
            self._swapper.initialize(state["params"])
        return state

    def _expand_rank_varying(self, state):
        """Give each rank-varying optimizer-state subtree (1-bit error
        feedback buffers, 0/1-Adam local momentum) a leading [dp] dim sharded
        over `data` — per-worker values that are explicit and checkpointable
        instead of silently divergent 'replicated' shards."""
        dp = self.plan.data
        mesh = self.mesh
        rv = set(self.optimizer.rank_varying)

        def expand_tree(tree, spec_tree_):
            sh = jax.tree.map(
                lambda s: NamedSharding(mesh, P("data", *s.spec)), spec_tree_,
                is_leaf=lambda x: isinstance(x, NamedSharding))
            fn = jax.jit(
                lambda t: jax.tree.map(
                    lambda a: jnp.broadcast_to(a[None], (dp,) + a.shape), t),
                out_shardings=sh)
            with mesh:
                out = fn(tree)
            return out, sh

        for k in list(state["opt"].keys()):
            if k in rv and state["opt"][k] is not None:
                state["opt"][k], sh = expand_tree(
                    state["opt"][k], self.state_shardings["opt"][k])
                self.state_shardings["opt"][k] = sh
        return state

    def _build_swapper(self, param_shapes):
        from deepspeed_tpu.runtime.swap_tensor import (HostAdamSwapper,
                                                       NVMeOptimizerSwapper)
        cfg = self.config
        off = cfg.zero_optimization.offload_optimizer
        p = dict(cfg.optimizer.params) if cfg.optimizer else {}
        name = _opt_name(cfg)
        if self._swap_storage == "cpu_adam":
            kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
            kw = dict(
                betas=tuple(p.get("betas", (0.9, 0.999))),
                eps=p.get("eps", 1e-10 if name == "adagrad" else 1e-8),
                weight_decay=p.get("weight_decay",
                                   0.01 if name == "adamw" else 0.0),
                adam_w_mode=(name == "adamw" or p.get("adam_w_mode", False)),
                bias_correction=p.get("bias_correction", True),
                param_shardings=self.param_shardings,
                compute_dtype=self.compute_dtype)
            if name == "adagrad":
                # host Adagrad tier rides the native swapper (the compute_on
                # flavor's tree update is Adam-only for now)
                return HostAdamSwapper(param_shapes, mesh=self.mesh,
                                       optim="adagrad", **kw)
            if (get_accelerator().platform != "cpu"
                    and "pinned_host" in kinds):
                # TPU-native flavor: Adam runs on the TPU host INSIDE the
                # XLA program (compute_on) over pinned-resident state — no
                # process-side grad fetch, so it's fast even when this
                # process is remote from the TPU host
                from deepspeed_tpu.runtime.swap_tensor import \
                    XlaHostAdamSwapper
                return XlaHostAdamSwapper(param_shapes, mesh=self.mesh, **kw)
            return HostAdamSwapper(param_shapes, mesh=self.mesh, **kw)
        grad_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self.grad_specs,
            is_leaf=lambda x: isinstance(x, P))
        return NVMeOptimizerSwapper(
            param_shapes, mesh=self.mesh, nvme_path=off.nvme_path,
            storage=self._swap_storage,
            betas=tuple(p.get("betas", (0.9, 0.999))), eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay",
                               0.01 if name == "adamw" else 0.0),
            adam_w_mode=(name == "adamw" or p.get("adam_w_mode", False)),
            bias_correction=p.get("bias_correction", True),
            chunk_elems=max(1, off.buffer_size // 4),  # buffer_size is bytes
            param_shardings=self.param_shardings,
            grad_shardings=grad_shardings,
            compute_dtype=self.compute_dtype,
            # both pipeline knobs off = the fully-drained swapper (the old
            # `... or True` ignored an explicit opt-out)
            pipeline=bool(off.pipeline_read or off.pipeline_write),
            aio_config=cfg.aio)

    def _build_infinity(self):
        from deepspeed_tpu.runtime.infinity import InfinityExecutor
        cfg = self.config
        off_p = cfg.zero_optimization.offload_param
        off_o = cfg.zero_optimization.offload_optimizer
        p = dict(cfg.optimizer.params) if cfg.optimizer else {}
        name = _opt_name(cfg)
        lr = self._schedule if self._schedule is not None else p.get("lr", 1e-3)
        import dataclasses as _dc
        model_cfg = _dc.replace(self.model.config, dtype=self.compute_dtype)
        return InfinityExecutor(
            model_cfg, rng=self._rng,
            backend=self._infinity_backend,
            nvme_path=off_p.nvme_path or off_o.nvme_path,
            lr=lr, betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay",
                               0.01 if name == "adamw" else 0.0),
            adam_w_mode=(name == "adamw" or p.get("adam_w_mode", False)),
            bias_correction=p.get("bias_correction", True),
            grad_clip=cfg.gradient_clipping or 0.0,
            param_cache_bytes=off_p.max_in_cpu,
            gas=cfg.gradient_accumulation_steps,
            mesh=self.mesh if self._infinity_multi else None,
            fp16=(dataclasses.asdict(cfg.fp16) if cfg.fp16.enabled else None),
            compression=self._compression,
            use_cpu_adam=off_o.use_cpu_adam,
            moq=self._moq is not None,
            # live cache only when the user set the knob: the reference
            # default (1e9) silently pinning ~2GB of bits in HBM could OOM
            # workloads sized without it
            max_live_params=(
                cfg.zero_optimization.stage3_max_live_parameters
                if cfg.zero_optimization.was_set("stage3_max_live_parameters")
                else 0),
            # overlapped offload pipeline: double-buffered layer streaming +
            # the three-way update sweep. The executor has ONE switch, so
            # turning BOTH knobs of EITHER offload section off drains it
            # (the offload-serial-pipeline corpus twin) — an explicit
            # opt-out on just offload_param must not be vetoed by
            # offload_optimizer's defaults
            pipeline=bool((off_p.pipeline_read or off_p.pipeline_write)
                          and (off_o.pipeline_read or off_o.pipeline_write)),
            aio_config=cfg.aio)

    def _state_shardings_from(self, state_shapes):
        """Build shardings for the full train-state pytree: params use
        param_specs, optimizer per-param tensors use opt_specs (ZeRO
        partitioning of master/moments), scalars replicate."""
        mesh = self.mesh
        param_leaves, param_treedef = jax.tree.flatten(
            jax.tree.map(lambda s: s, self.param_specs,
                         is_leaf=lambda x: isinstance(x, P)))
        opt_spec_tree = self.opt_specs

        def shard_like_params(subtree_shapes, specs):
            return jax.tree.map(
                lambda sh, sp: NamedSharding(mesh, sp),
                subtree_shapes, specs, is_leaf=lambda x: hasattr(x, "shape"))

        params_shapes = state_shapes["params"]

        def assign(sub):
            """Recursively walk the optimizer state: any subtree whose pytree
            structure matches the params tree gets the ZeRO opt-state specs
            (covers our dict optimizers AND optax NamedTuple states); scalars
            and everything else replicate."""
            if sub is None:
                return None
            if _same_structure(sub, params_shapes):
                return shard_like_params(sub, opt_spec_tree)
            if hasattr(sub, "shape"):  # leaf
                return NamedSharding(mesh, P())
            if isinstance(sub, dict):
                return {k: assign(v) for k, v in sub.items()}
            if isinstance(sub, tuple) and hasattr(sub, "_fields"):  # namedtuple
                return type(sub)(*[assign(v) for v in sub])
            if isinstance(sub, (tuple, list)):
                return type(sub)(assign(v) for v in sub)
            return jax.tree.map(lambda s: NamedSharding(mesh, P()), sub)

        out = {}
        # reuse the prebuilt param shardings (they may carry memory kinds,
        # e.g. pinned_host layer stacks under offload_param)
        out["params"] = self.param_shardings
        out["opt"] = assign(state_shapes["opt"])
        if self._offload_opt:
            # the jitted step stays memory-kind-free (XLA SPMD drops sharding
            # attributes on placement custom-calls for replicated tensors);
            # host residency is managed EAGERLY at step boundaries instead
            self._opt_host_shardings = jax.tree.map(
                lambda s: NamedSharding(s.mesh, s.spec, memory_kind="pinned_host")
                if s is not None else None,
                out["opt"], is_leaf=lambda x: x is None or isinstance(x, NamedSharding))
        out["step"] = NamedSharding(mesh, P())
        if "loss_scale" in state_shapes:
            out["loss_scale"] = jax.tree.map(
                lambda s: NamedSharding(mesh, P()), state_shapes["loss_scale"])
        if "skipped" in state_shapes:
            out["skipped"] = NamedSharding(mesh, P())
        if "telemetry" in state_shapes:
            out["telemetry"] = jax.tree.map(
                lambda s: NamedSharding(mesh, P()), state_shapes["telemetry"])
        return out

    # ------------------------------------------------------------------
    def _batch_spec(self):
        # expert groups consume distinct data (expert-data-parallelism);
        # sequence dim shards over `seq` when sequence parallelism is on
        from deepspeed_tpu.parallel.mesh import BATCH_AXES
        if self.plan.seq > 1:
            return P(BATCH_AXES, "seq")
        return P(BATCH_AXES)

    @staticmethod
    def _accum_micro_grads(micro_fn, params, batch, gas: int, rng,
                           postprocess=None, unroll: int = 0):
        """Gradient accumulation over `gas` microbatches, shared by the dense
        GSPMD step, the deferred-sync shard_map body, and the 1-bit shard_map
        step. micro_fn(params, mb, rng) -> (loss, grads); postprocess (e.g. a
        sharding constraint) is applied to the running accumulator. The 1/gas
        mean scaling is FOLDED into the accumulator update (one fused
        multiply-add inside the loop) instead of a separate post-scan sweep
        over the full grad tree. unroll >= gas fully unrolls the microbatch
        loop (comm.microbatch_unroll: per-microbatch collectives become
        distinct schedulable sites). Returns (summed grads / gas, mean
        loss)."""
        if gas == 1:
            loss, grads = micro_fn(params, batch, rng)
            return grads, loss

        def split(x):
            if getattr(x, "ndim", 0) == 0:  # scalar side-channel (e.g.
                return jnp.broadcast_to(x, (gas,))  # _pld_theta): replicate
            return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])

        if isinstance(batch, dict):
            mbs = {k: (jnp.broadcast_to(v, (gas,) + jnp.shape(v))
                       if _is_side_channel(k) else split(v))
                   for k, v in batch.items()}
        else:
            mbs = jax.tree.map(split, batch)
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        if postprocess is not None:
            zeros = postprocess(zeros)
        inv_gas = np.float32(1.0 / gas)

        def body(acc, mb_rng):
            mb, r = mb_rng
            loss, g = micro_fn(params, mb, r)
            acc = jax.tree.map(lambda a, gg: a + gg * inv_gas, acc, g)
            if postprocess is not None:
                acc = postprocess(acc)
            return acc, loss

        rngs = jax.random.split(rng, gas)
        grads, losses = jax.lax.scan(
            body, zeros, (mbs, rngs),
            unroll=True if unroll >= gas else max(1, int(unroll)))
        return grads, jnp.mean(losses)

    def _compile_steps(self):
        cfg = self.config
        # in pipeline mode grad accumulation IS the microbatch rotation inside
        # the pipelined loss; the outer step consumes the whole global batch
        gas = 1 if self._pp_mode else cfg.gradient_accumulation_steps
        mesh = self.mesh
        grad_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                                      self.grad_specs,
                                      is_leaf=lambda x: isinstance(x, P))
        model = self.model
        fp16 = self._fp16
        fp16_cfg = cfg.fp16
        clip = cfg.gradient_clipping
        compute_dtype = self.compute_dtype

        compression = self._compression

        moq = self._moq

        tel_on = self._tel_in_graph
        tel_ratio = tel_on and cfg.telemetry.update_ratio

        # --- communication scheduling (comm.schedule: deferred grad sync +
        # hierarchical 2D-mesh reduction; reference: overlap_comm /
        # contiguous_gradients / no_sync in runtime/zero/stage_1_and_2.py)
        from deepspeed_tpu.comm import schedule as comm_sched
        ccfg = cfg.comm
        unroll = max(0, int(ccfg.microbatch_unroll))
        self._microbatch_unroll = unroll  # one derivation; onebit reads it
        self._deferred_sync = False
        self._hier_reduce = False
        if ccfg.hierarchical_grad_reduce:
            if self.plan.data > 1 and self.plan.fsdp > 1:
                self._hier_reduce = True
            else:
                logger.info("comm.hierarchical_grad_reduce is a no-op: needs "
                            "a 2D data x fsdp mesh "
                            f"(have {self.plan.describe()})")
        if ccfg.deferred_grad_sync:
            if self._onebit_comm:
                logger.info(
                    "comm.deferred_grad_sync: the 1-bit shard_map step is "
                    "already deferred by construction (grads accumulate "
                    "per-device local; only the phase collective crosses "
                    "the wire at the boundary)")
            elif self._nvme_opt or self._infinity or self._pp_mode:
                logger.warning(
                    "comm.deferred_grad_sync ignored: host-driven optimizer "
                    "paths and pipeline mode keep their own step structure")
            else:
                ok, why = comm_sched.deferred_supported(self.plan)
                if not ok:
                    logger.warning(f"comm.deferred_grad_sync ignored: {why}")
                elif self.plan.data <= 1:
                    logger.info(
                        "comm.deferred_grad_sync: no `data` axis to defer "
                        "over (dp rides fsdp; per-use reductions are ZeRO-3 "
                        "semantics) — eager path unchanged")
                else:
                    self._deferred_sync = True
                    logger.info(
                        "comm.deferred_grad_sync: microbatch grads "
                        "accumulate in a per-device local buffer; ONE "
                        f"data-axis sync per step (gas={gas})"
                        + (", hierarchical fsdp-phase reduction"
                           if self._hier_reduce else ""))
        # accumulator target specs: the hierarchical hint pins the fsdp-
        # sharded intermediate the data-axis phase operates on
        acc_specs = self.grad_specs
        if self._hier_reduce:
            acc_specs = comm_sched.hierarchical_tree(
                self.grad_specs, self._shape_tree, self.plan)
        deferred = self._deferred_sync
        hier = self._hier_reduce
        plan = self.plan
        local_acc_specs = None
        deferred_unroll = unroll
        if deferred:
            local_ = comm_sched.local_tree(acc_specs)
            if any(len(s) for s in jax.tree.leaves(
                    local_, is_leaf=lambda x: isinstance(x, P))):
                local_acc_specs = local_
            # a lax.scan INSIDE the manual-over-data region trips an XLA
            # SPMD check (hlo_sharding_util IsManualSubgroup) whenever a
            # size>1 AUTO axis exists (fsdp/tensor 2D meshes) — unroll the
            # microbatch loop there; pure-data meshes keep the scan
            if any(v > 1 for a, v in plan.axis_sizes().items()
                   if a != "data"):
                deferred_unroll = max(unroll, gas)

        def micro_grads(params, mb, rng, scale, step=None, specs="grad"):
            def loss_fn(p):
                if compression is not None:
                    p = compression.apply(p, step if step is not None else 0)
                if moq is not None and "_moq_bits" in mb:
                    p = moq.apply(p, mb["_moq_bits"])
                loss = model.loss_fn(p, mb, rng, False)
                if fp16:
                    loss = loss * scale.astype(loss.dtype)
                return loss
            loss, grads = jax.value_and_grad(loss_fn)(params)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            if specs == "grad":
                specs = self.grad_specs
            if specs is not None:
                grads = jax.lax.with_sharding_constraint(grads, specs)
            return loss, grads

        def apply_grads(state, grads, mean_loss):
            """Unscale, clip, optimizer, loss-scale update, overflow skip."""
            params, opt = state["params"], state["opt"]
            if fp16:
                ls = fp16_mod.LossScaleState(**state["loss_scale"])
                grads = fp16_mod.unscale_grads(grads, ls)
                overflow = fp16_mod.has_overflow(grads)
            else:
                overflow = jnp.zeros((), jnp.bool_)
            gnorm = global_grad_norm(grads)
            if clip and clip > 0:
                scale_c = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * scale_c, grads)
            new_params, new_opt = self.optimizer.update(grads, opt, params)
            if fp16:
                # skip the step on overflow (reference: step:1635 overflow path)
                # (both trees are in device memory here — where() before the
                # host writeback)
                new_params = jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n), new_params, params)
                new_opt = jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n), new_opt, opt)
            if fp16:
                new_ls = fp16_mod.update_loss_scale(
                    ls, overflow, dynamic=fp16_cfg.dynamic,
                    scale_window=fp16_cfg.loss_scale_window,
                    min_scale=fp16_cfg.min_loss_scale,
                    max_hysteresis=fp16_cfg.hysteresis,
                    consecutive_hysteresis=fp16_cfg.consecutive_hysteresis)
                loss_scale_state = {"scale": new_ls.scale,
                                    "good_steps": new_ls.good_steps,
                                    "hysteresis": new_ls.hysteresis}
            else:
                loss_scale_state = None
            # applied-update counter: does not advance on a skipped (overflow)
            # step, mirroring the reference's optimizer-step accounting
            new_step = jnp.where(overflow, state["step"], state["step"] + 1)
            new_state = {"params": new_params, "opt": new_opt, "step": new_step}
            if loss_scale_state is not None:
                new_state["loss_scale"] = loss_scale_state
            if fp16:
                # in-graph skip counter: no per-step bool(overflow) fetch on
                # the host — skipped_steps/get_lr read this lazily at
                # steps_per_print boundaries
                new_state["skipped"] = (state["skipped"]
                                        + overflow.astype(jnp.int32))
            if tel_on:
                # in-graph telemetry accumulators: scalar ops over values the
                # step already computed (zero added syncs; the update/param
                # norms are the only extra reductions, and only when
                # telemetry.update_ratio is on)
                ratio = (tel_acc.update_to_param_ratio(new_params, params)
                         if tel_ratio else None)
                new_state["telemetry"] = tel_acc.accumulate(
                    state["telemetry"], loss=mean_loss, gnorm=gnorm,
                    overflow=overflow, update_ratio=ratio)
            metrics = {"loss": mean_loss, "grad_norm": gnorm,
                       "overflow": overflow}
            if fp16:
                metrics["loss_scale"] = state["loss_scale"]["scale"]
            return new_state, metrics

        def deferred_batch_grads(params, batch, rng, scale, step):
            """Deferred sync: grad accumulation runs manual over `data`
            (everything else stays auto/GSPMD). Each device accumulates the
            LOCAL (unreduced) grad sum across all `gas` microbatches — no
            data-axis collective can exist inside the scan — and
            comm.schedule.boundary_reduce issues the ONE reduction at the
            step boundary (psum_scatter onto dp-sharded grad specs, psum
            for replicated leaves). DeepSpeed no_sync semantics: dp-sync
            collective counts are independent of gas."""
            def local_body(params, batch, rng, scale, step):
                grads, mean_loss = self._accum_micro_grads(
                    lambda p, mb, r: micro_grads(p, mb, r, scale, step=step,
                                                 specs=local_acc_specs),
                    params, batch, gas, rng,
                    postprocess=(None if local_acc_specs is None else
                                 lambda t: jax.lax.with_sharding_constraint(
                                     t, local_acc_specs)),
                    unroll=deferred_unroll)
                grads = comm_sched.boundary_reduce(grads, self.grad_specs,
                                                   plan)
                mean_loss = jax.lax.pmean(mean_loss, "data")
                return grads, mean_loss

            fn = comm_sched.shard_map_compat(
                local_body, mesh,
                in_specs=(jax.tree.map(lambda _: P(), params),
                          _manual_batch_specs(batch), P(), P(), P()),
                out_specs=(comm_sched.manual_out_spec(self.grad_specs), P()),
                manual_axes=("data",))
            grads, mean_loss = fn(params, batch, rng, scale, step)
            # pin the final placement: the scattered data dim plus whatever
            # auto-axis sharding rode out of the region lands on grad_specs
            grads = jax.lax.with_sharding_constraint(grads, self.grad_specs)
            return grads, mean_loss

        def batch_grads(state, batch, rng):
            """Averaged grads + mean loss over `gas` microbatches.
            batch leaves: [global_batch, ...], sharded over (data, fsdp)."""
            params = state["params"]
            scale = state["loss_scale"]["scale"] if fp16 else jnp.float32(1.0)
            if deferred:
                grads, mean_loss = deferred_batch_grads(
                    params, batch, rng, scale, state["step"])
            else:
                grads, mean_loss = self._accum_micro_grads(
                    lambda p, mb, r: micro_grads(p, mb, r, scale,
                                                 step=state["step"],
                                                 specs=acc_specs),
                    params, batch, gas, rng,
                    postprocess=lambda t: jax.lax.with_sharding_constraint(
                        t, acc_specs),
                    unroll=unroll)
                if hier:
                    # phase 2 hint: the fsdp-sharded buffer resharded onto
                    # the final grad placement
                    grads = jax.lax.with_sharding_constraint(
                        grads, self.grad_specs)
            if fp16:
                mean_loss = mean_loss / scale
            return mean_loss, grads

        def train_step(state, batch, rng):
            """One full optimizer step over `gas` microbatches. The named
            scopes land in the compiled program's op_name metadata — the
            perf doctor's trace join reads them to split device time into
            grad-compute vs optimizer phases."""
            with jax.named_scope("grads"):
                mean_loss, grads = batch_grads(state, batch, rng)
            with jax.named_scope("optimizer"):
                return apply_grads(state, grads, mean_loss)

        # raw (unjitted) step for the fused K-step program; recompiles
        # (Random-LTD/act-quant rebuilds) invalidate any cached fusions
        self._train_step_fn = train_step
        self._fused_steps = {}

        if self._nvme_opt:
            # optimizer apply happens chunk-wise through the NVMe swapper;
            # only the grad computation is a monolithic jitted program
            self._batch_grads = jax.jit(
                batch_grads,
                in_shardings=(self.state_shardings, None, None),
                out_shardings=(None, grad_shardings))
            self._train_step = None
        else:
            self._train_step = jax.jit(
                train_step,
                in_shardings=(self.state_shardings, None, None),
                out_shardings=(self.state_shardings, None),
                donate_argnums=(0,))

        if self._onebit_comm:
            # phase-compiled shard_map steps replace the GSPMD train step:
            # dense pmean in the warm program, 1-bit packed all-gather in the
            # compressed program, no collective at all in a local program
            self._train_step = None
            self._onebit_steps = {}
            # host mirror of opt["step"] driving phase selection; synced from
            # device state so mid-run recompiles (e.g. Random-LTD rebuilds)
            # and load_checkpoint cannot restart the warmup phase
            if getattr(self, "state", None) is not None:
                self._onebit_applied = int(np.asarray(jax.device_get(
                    self.state["opt"]["step"]))[0])
            else:
                self._onebit_applied = 0

        def eval_step(state, batch):
            p = state["params"]
            if compression is not None:
                p = compression.apply(p, state["step"])
            loss = model.loss_fn(p, batch, None, True)
            return loss

        self._eval_step = jax.jit(
            eval_step, in_shardings=(self.state_shardings, None))

        # --- 3-call API pieces (forward/backward/step)
        def grad_only(state, batch, rng):
            scale = state["loss_scale"]["scale"] if fp16 else jnp.float32(1.0)
            loss, grads = micro_grads(state["params"], batch, rng, scale,
                                      step=state["step"])
            return (loss / scale if fp16 else loss), grads

        self._grad_only = jax.jit(
            grad_only, in_shardings=(self.state_shardings, None, None),
            out_shardings=(None, grad_shardings))
        self._accum = jax.jit(
            lambda acc, g: jax.tree.map(jnp.add, acc, g),
            in_shardings=(grad_shardings, grad_shardings),
            out_shardings=grad_shardings, donate_argnums=(0,))
        if self._nvme_opt:
            self._apply = None  # step() routes through _nvme_apply
        else:
            self._apply = jax.jit(
                lambda state, grads, loss: apply_grads(
                    state, jax.tree.map(lambda g: g / gas, grads), loss),
                in_shardings=(self.state_shardings, grad_shardings, None),
                out_shardings=(self.state_shardings, None), donate_argnums=(0, 1))

    # ------------------------------------------------------------------
    # 1-bit compressed step (shard_map over data; grads never dense-reduced
    # in the compressed phase — reference: runtime/comm/nccl.py:53)
    # ------------------------------------------------------------------
    def _get_onebit_step(self, phase: str, batch=None):
        if phase in self._onebit_steps:
            return self._onebit_steps[phase]
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        mesh = self.mesh
        model = self.model
        opt = self.optimizer
        rv = set(opt.rank_varying)
        from jax import lax

        fp16 = self._fp16
        fp16_cfg = cfg.fp16
        clip = cfg.gradient_clipping
        compression = self._compression
        moq = self._moq
        tel_on = self._tel_in_graph
        tel_ratio = tel_on and cfg.telemetry.update_ratio

        def per_device(state, batch, rng):
            params = state["params"]
            step = state["step"]
            opt_local = {
                k: (jax.tree.map(lambda a: jnp.squeeze(a, 0), v)
                    if k in rv and v is not None else v)
                for k, v in state["opt"].items()}
            rng = jax.random.fold_in(rng, lax.axis_index("data"))
            scale = (state["loss_scale"]["scale"] if fp16
                     else jnp.float32(1.0))

            def micro(p, mb, r):
                def loss_fn(q):
                    if compression is not None:
                        # same traced param transform the GSPMD step
                        # applies (micro_grads above); masks/quant see the
                        # per-device replicated params, schedule driven by
                        # the traced step
                        q = compression.apply(q, step)
                    if moq is not None and "_moq_bits" in mb:
                        q = moq.apply(q, mb["_moq_bits"])
                    loss = model.loss_fn(q, mb, r, False)
                    return loss * scale.astype(loss.dtype) if fp16 else loss
                return jax.value_and_grad(loss_fn)(p)

            # already deferred by construction: grads stay per-device local
            # across the whole accumulation; comm.microbatch_unroll still
            # applies (schedulable per-microbatch compute sites)
            grads, loss = self._accum_micro_grads(
                lambda p, mb, r: micro(p, mb, r), params, batch, gas, rng,
                unroll=self._microbatch_unroll)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            if fp16:
                grads = fp16_mod.unscale_grads(
                    grads, fp16_mod.LossScaleState(**state["loss_scale"]))
                loss = loss / scale
                # ANY rank overflowing must skip the step on EVERY rank —
                # divergent skips would desynchronize the replicated params
                overflow = lax.pmax(
                    fp16_mod.has_overflow(grads).astype(jnp.float32),
                    "data") > 0
            else:
                overflow = jnp.zeros((), jnp.bool_)

            # RMS of the per-rank local grad norms — an UPPER bound on the
            # true norm of the averaged gradient (computing that exactly
            # would need the dense all-reduce this path avoids). The scalar
            # psum makes it IDENTICAL on every rank, so clipping by it
            # cannot desynchronize parameters.
            gsq = sum(jnp.sum(jnp.square(g))
                      for g in jax.tree.leaves(grads))
            gnorm = jnp.sqrt(lax.pmean(gsq, "data"))
            if clip and clip > 0:
                coef = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * coef, grads)

            new_params, new_opt = opt.update_phase(
                grads, opt_local, params, phase=phase, axis="data")
            if fp16:
                # freeze EVERYTHING on overflow (params, moments, error
                # feedback) — reference: step:1635 overflow path
                new_params = jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n),
                    new_params, params)
                new_opt = jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n),
                    new_opt, opt_local)
            new_opt = {
                k: (jax.tree.map(lambda a: a[None], v)
                    if k in rv and v is not None else v)
                for k, v in new_opt.items()}
            mean_loss = lax.pmean(loss, "data")
            new_state = {"params": new_params, "opt": new_opt,
                         "step": jnp.where(overflow, state["step"],
                                           state["step"] + 1)}
            if fp16:
                new_ls = fp16_mod.update_loss_scale(
                    fp16_mod.LossScaleState(**state["loss_scale"]), overflow,
                    dynamic=fp16_cfg.dynamic,
                    scale_window=fp16_cfg.loss_scale_window,
                    min_scale=fp16_cfg.min_loss_scale,
                    max_hysteresis=fp16_cfg.hysteresis,
                    consecutive_hysteresis=fp16_cfg.consecutive_hysteresis)
                new_state["loss_scale"] = {"scale": new_ls.scale,
                                           "good_steps": new_ls.good_steps,
                                           "hysteresis": new_ls.hysteresis}
                new_state["skipped"] = (state["skipped"]
                                        + overflow.astype(jnp.int32))
            if tel_on:
                # inputs (pmean'd loss/gnorm, pmax'd overflow) and the
                # replicated params are rank-identical, so the accumulated
                # leaf stays rank-identical — its out_spec is P()
                ratio = (tel_acc.update_to_param_ratio(new_params, params)
                         if tel_ratio else None)
                new_state["telemetry"] = tel_acc.accumulate(
                    state["telemetry"], loss=mean_loss, gnorm=gnorm,
                    overflow=overflow, update_ratio=ratio)
            metrics = {"loss": mean_loss, "grad_norm": gnorm,
                       "overflow": overflow}
            if fp16:
                metrics["loss_scale"] = state["loss_scale"]["scale"]
            return new_state, metrics

        def spec_of(tree, varying_keys=()):
            return {k: (P("data") if k in varying_keys else P())
                    for k in tree}

        state_spec = {"params": P(),
                      "opt": spec_of(self.state["opt"], rv),
                      "step": P()}
        if fp16:
            state_spec["loss_scale"] = {k: P() for k in
                                        self.state["loss_scale"]}
            state_spec["skipped"] = P()
        if tel_on:
            state_spec["telemetry"] = {k: P() for k in
                                       self.state["telemetry"]}
        out_metrics_spec = {"loss": P(), "grad_norm": P(), "overflow": P()}
        if fp16:
            out_metrics_spec["loss_scale"] = P()
        batch_spec = _manual_batch_specs(batch)
        fn = jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(state_spec, batch_spec, P()),
            out_specs=(state_spec, out_metrics_spec),
            axis_names={"data"}, check_vma=False)
        step_fn = jax.jit(fn, in_shardings=(self.state_shardings, None, None),
                          out_shardings=(self.state_shardings, None),
                          donate_argnums=(0,))
        self._onebit_steps[phase] = step_fn
        return step_fn

    # ------------------------------------------------------------------
    # primary API
    # ------------------------------------------------------------------
    def train_batch(self, batch) -> Dict[str, Any]:
        """Consume one *global* batch (train_batch_size rows) and take one
        optimizer step (reference: PipelineEngine.train_batch:282 semantics,
        also covers engine fwd/bwd/step loop for non-pipe)."""
        self._activate_context()
        self.tput_timer.start()
        if self._tracer is not None:
            # windowed jax.profiler capture (telemetry.trace) — a no-op
            # outside the configured window
            self._tracer.maybe_profile(self.global_steps)
        self._rng, sub = jax.random.split(self._rng)
        if self._act_quant and not self._act_quant_on and \
                self.global_steps + 1 >= self._act_quant[1]:
            self._rebuild_act_quant(self.model)
            self._compile_steps()
        if self._curriculum is not None:
            from deepspeed_tpu.runtime.data_pipeline import (
                apply_seqlen_curriculum)
            d = self._curriculum.update_difficulty(self.global_steps + 1)
            batch = apply_seqlen_curriculum(batch, d)
        if self._ltd is not None:
            self._maybe_rebuild_ltd(batch)
        if self._pld is not None:
            theta_min, gamma = self._pld
            theta = ((1.0 - theta_min) * math.exp(-gamma * self.global_steps)
                     + theta_min)
            batch = dict(batch)
            batch["_pld_theta"] = np.float32(theta)  # traced input: the
            # continuously-decaying theta must not retrigger compilation
        if self._moq is not None:
            if self._moq.wants_eigenvalues(self.global_steps) \
                    and self.state is not None:
                evs = self._moq.layer_eigenvalues(
                    self.model.loss_fn, self.state["params"],
                    self._device_batch(batch), rng=sub)
                self._moq.update_eigenvalues(evs, self.global_steps)
            batch = dict(batch)
            # traced [L] side-channel: schedule/eigenvalue updates must not
            # retrigger compilation
            batch["_moq_bits"] = self._moq.bits(self.global_steps)
        if self._infinity:
            # unsharded single-device executor: no mesh batch placement.
            # The executor is host-driven per step, so overflow is already
            # a host value — account it on the host offset directly
            metrics = self._infinity_exec.train_batch(batch)
            self.global_steps += 1
            self.micro_steps += self.config.gradient_accumulation_steps
            if self._fp16 and bool(metrics.get("overflow")):
                self._skipped_offset += 1
            self._tel_anchor()
            self.tput_timer.stop(output=metrics)
            self._log_step(dict(metrics))
            return metrics
        batch = self._device_batch(batch)
        with self._tel_span("dispatch"):
            if self._nvme_opt:
                with self.mesh:
                    mean_loss, grads = self._batch_grads(self.state, batch,
                                                         sub)
                metrics = self._nvme_apply(grads, mean_loss)
            elif self._onebit_comm:
                phase = self.optimizer.phase_for(self._onebit_applied)
                step_fn = self._get_onebit_step(phase, batch)
                self._capture_static_args(step_fn, (self.state, batch, sub), 1)
                with self.mesh:
                    self.state, metrics = step_fn(self.state, batch, sub)
                # EXPLICIT sync point: the warm->compressed phase switch is a
                # host decision keyed on the applied-update count, so this
                # path pays one overflow fetch per step by design (skip
                # accounting itself stays in-graph — state["skipped"])
                if not (self._fp16 and bool(metrics["overflow"])):
                    self._onebit_applied += 1  # overflow steps don't advance
            else:
                if self._offload_opt:
                    self.state["opt"] = self._opt_to_device(self.state["opt"])
                self._capture_static_args(
                    self._train_step, (self.state, batch, sub), 1)
                with self.mesh:
                    self.state, metrics = self._train_step(self.state, batch,
                                                           sub)
                if self._offload_opt:
                    self.state["opt"] = self._opt_to_host(self.state["opt"])
        self.global_steps += 1
        self.micro_steps += self.config.gradient_accumulation_steps
        self._tel_anchor()
        # no host overflow fetch here: skip accounting is in-graph for the
        # jitted paths (reference step:1635 does it eagerly; the eager bool()
        # was the per-step stall this engine removes), and _nvme_apply
        # already accounted its host-side overflow
        self.tput_timer.stop(output=metrics)
        metrics = {k: v for k, v in metrics.items()}
        self._log_step(metrics)
        fp_cfg = self.config.flops_profiler
        if (fp_cfg.enabled and not getattr(self, "_profiling", False)
                and self.global_steps == fp_cfg.profile_step):
            from deepspeed_tpu.profiling import FlopsProfiler
            self._profiling = True  # run() drives train_batch to time steps
            try:
                self.flops_profile = FlopsProfiler(fp_cfg).run(self, batch)
            finally:
                self._profiling = False
        return metrics

    # ------------------------------------------------------------------
    # async multi-step pipeline (train_batches)
    # ------------------------------------------------------------------
    def train_batches(self, data_iter, num_steps: int) -> Dict[str, Any]:
        """Async multi-step train loop: consume `num_steps` global batches
        from `data_iter` keeping up to ``pipeline.in_flight`` dispatched
        steps in flight.

        Because overflow/skip accounting lives in the donated jitted state,
        the host never waits on step N to decide step N+1: each iteration
        dispatches and moves on, bounded by blocking on the (i-in_flight)'th
        step's output so dispatch can't run away from execution. With
        ``pipeline.prefetch`` the sharding-aware device_put of batch N+1
        overlaps step N; with ``pipeline.fuse_steps`` K>1 (plain dense path
        only) K sequential optimizer steps compile into ONE dispatch.
        Metric fetches happen only at steps_per_print boundaries
        (_log_step). Returns the LAST step's metrics — device arrays;
        float() them to force the final sync.

        The reference has no equivalent single call: its train loop hides
        Python overhead behind CUDA streams but still reads the overflow
        flag every step (engine step:1635)."""
        import collections
        import itertools
        self._activate_context()
        pcfg = self.config.pipeline
        in_flight = max(1, int(pcfg.in_flight))
        k = max(1, int(pcfg.fuse_steps))
        use_fused = k > 1 and self._can_fuse()
        if k > 1 and not use_fused:
            logger.warning(
                "pipeline.fuse_steps ignored: the fused program needs the "
                "plain dense jitted path (no 1-bit/NVMe/infinity executor, "
                "no per-step batch rewrites)")
        it = itertools.islice(iter(data_iter), num_steps)
        if not use_fused and pcfg.prefetch and not self._infinity:
            from deepspeed_tpu.runtime.dataloader import PrefetchLoader
            it = iter(PrefetchLoader(it, put_fn=self._device_batch,
                                     tracer=self._tracer))
        _span = self._tel_span
        window = collections.deque()
        metrics = None
        done = 0
        while done < num_steps:
            if use_fused and num_steps - done >= k:
                with _span("data_wait"):
                    chunk = list(itertools.islice(it, k))
                if not chunk:
                    break
                if len(chunk) < k:
                    # short read: run the tail through the single-step path
                    # below rather than jit-compiling a one-off smaller
                    # fused program
                    for batch in chunk:
                        metrics = self.train_batch(batch)
                        done += 1
                    break
                metrics = self._train_batch_fused(chunk)
                done += k
            else:
                try:
                    with _span("data_wait"):
                        batch = next(it)
                except StopIteration:
                    break
                metrics = self.train_batch(batch)
                done += 1
            window.append(metrics["loss"])
            if len(window) > in_flight:
                # bound host run-ahead: wait for the oldest in-flight step
                # before dispatching further (backpressure, not a stall —
                # in_flight-1 steps are still queued behind it). The tracer's
                # "block" span is the dispatch-stall signal the anomaly
                # detector watches.
                with _span("block"):
                    jax.block_until_ready(window.popleft())
        if done < num_steps:
            logger.warning(f"train_batches: iterator exhausted after {done} "
                           f"of {num_steps} steps")
        return metrics

    def _can_fuse(self) -> bool:
        """The fused K-step program covers the plain dense jitted path only:
        host-driven executors (1-bit phase switch, NVMe swapper, infinity)
        and per-step host batch rewrites (curriculum/LTD/PLD/MoQ, a pending
        act-quant rebuild) need step granularity."""
        return (self._train_step is not None and not self._onebit_comm
                and not self._nvme_opt and not self._infinity
                and not self._offload_opt
                and self._curriculum is None and self._ltd is None
                and self._pld is None and self._moq is None
                and (not self._act_quant or self._act_quant_on)
                and not self.config.flops_profiler.enabled)

    def _get_fused_step(self, k: int):
        """Jitted K-step program: the train state threads through K
        sequential (unrolled) optimizer steps in ONE dispatch, donated
        end-to-end. Per-step collectives scale exactly Kx — the analysis
        census pins that (a collective hoisted out of or duplicated into
        the unrolled loop is census drift)."""
        fn = self._fused_steps.get(k)
        if fn is not None:
            return fn
        step_fn = self._train_step_fn
        state_sh = self.state_shardings

        def fused(state, batches, rngs):
            out = []
            for i in range(k):
                mb = jax.tree.map(lambda x: x[i], batches)
                state, m = step_fn(state, mb, rngs[i])
                # pin the inter-step state to the program-boundary shardings:
                # without this GSPMD reshards the unrolled interior freely
                # and the collective census stops being Kx the single step
                state = jax.tree.map(
                    lambda x, s: jax.lax.with_sharding_constraint(x, s)
                    if s is not None else x,
                    state, state_sh,
                    is_leaf=lambda x: x is None)
                out.append(m)
            metrics = jax.tree.map(lambda *xs: jnp.stack(xs), *out)
            return state, metrics

        fn = jax.jit(fused,
                     in_shardings=(self.state_shardings, None, None),
                     out_shardings=(self.state_shardings, None),
                     donate_argnums=(0,))
        self._fused_steps[k] = fn
        return fn

    def _train_batch_fused(self, batches) -> Dict[str, Any]:
        """Dispatch ONE jitted program covering len(batches) sequential
        optimizer steps (host batches stacked on a leading step dim).
        Bookkeeping matches that many train_batch calls; the returned
        metrics are the last sub-step's, still device-resident."""
        k = len(batches)
        self.tput_timer.start()
        if self._tracer is not None:
            self._tracer.maybe_profile(self.global_steps)
        self._rng, sub = jax.random.split(self._rng)
        rngs = jax.random.split(sub, k)
        placed = self._device_batches(_stack_batches(batches))
        fused_fn = self._get_fused_step(k)
        self._capture_static_args(fused_fn, (self.state, placed, rngs), k)
        with self._tel_span("dispatch"):
            with self.mesh:
                self.state, metrics_k = fused_fn(self.state, placed, rngs)
        self.global_steps += k
        self.micro_steps += k * self.config.gradient_accumulation_steps
        self._tel_anchor()
        metrics = jax.tree.map(lambda v: v[-1], metrics_k)  # lazy slice
        self.tput_timer.stop(output=metrics, steps=k)
        self._log_step(dict(metrics))
        return metrics

    def _rebuild_act_quant(self, model):
        """Swap in the activation-quantized model config (one recompile —
        the traced alternative would carry a dead branch every step)."""
        import dataclasses as _dc
        from deepspeed_tpu.models import make_model as _mk
        bits = self._act_quant[0]
        model = _mk(_dc.replace(model.config, activation_quant_bits=bits),
                    name=model.name)
        self.model = model
        self._act_quant_on = True
        logger.info(f"activation quantization active: {bits}-bit STE on "
                    "post-norm activations")
        return model

    def _maybe_rebuild_ltd(self, batch):
        """Random-LTD: the kept-token count is a SHAPE, so when the schedule
        crosses a bucket boundary the model + step programs are rebuilt (jit
        caches the old buckets; a handful of compiles per run)."""
        seq_leaves = [v for v in batch.values()
                      if hasattr(v, "ndim") and v.ndim >= 2]
        if not seq_leaves:
            return
        S = seq_leaves[0].shape[1]
        k = self._ltd.kept_tokens(self.global_steps + 1, S)
        if k == self._ltd_keep:
            return
        import dataclasses as _dc
        from deepspeed_tpu.models import make_model
        base = self.model.config
        active = k < S
        # saturated schedule -> back to the dense scanned stack (unrolled
        # layers are only needed while LTD wraps individual layers)
        self.model = make_model(_dc.replace(
            base, random_ltd=active, random_ltd_keep=k,
            scan_layers=self._ltd_orig_scan if not active else False),
            name=self.model.name)
        self._ltd_keep = k
        logger.info(f"random-ltd: kept tokens -> {k} (of {S})")
        self._compile_steps()

    def _nvme_apply(self, grads, mean_loss) -> Dict[str, Any]:
        """Optimizer apply through the NVMe swapper (ZeRO-Infinity path).
        Grad scale/overflow handling happens host-side: on overflow the NVMe
        state is untouched and only the loss scale shrinks."""
        scale = float(self.state["loss_scale"]["scale"]) if self._fp16 else 1.0
        applied = int(np.asarray(jax.device_get(self.state["step"]))) + 1
        new_params, gnorm, overflow = self._swapper.step(
            grads, lr=self.get_lr(), step_num=applied,
            clip=self.config.gradient_clipping, grad_scale=scale)
        if not overflow:
            self.state["params"] = new_params
            self.state["step"] = jax.tree.map(lambda s: s + 1, self.state["step"])
        elif self._fp16:
            # host-driven path: overflow is already a host bool here, so the
            # skip lands on the host offset (the device counter stays 0)
            self._skipped_offset += 1
        if self._fp16:
            ls = fp16_mod.LossScaleState(
                scale=jnp.asarray(scale, jnp.float32),
                good_steps=self.state["loss_scale"]["good_steps"],
                hysteresis=self.state["loss_scale"]["hysteresis"])
            cfgf = self.config.fp16
            new_ls = fp16_mod.update_loss_scale(
                ls, jnp.asarray(overflow), dynamic=cfgf.dynamic,
                scale_window=cfgf.loss_scale_window,
                min_scale=cfgf.min_loss_scale, max_hysteresis=cfgf.hysteresis,
                consecutive_hysteresis=cfgf.consecutive_hysteresis)
            self.state["loss_scale"] = {"scale": new_ls.scale,
                                        "good_steps": new_ls.good_steps,
                                        "hysteresis": new_ls.hysteresis}
        metrics = {"loss": mean_loss, "grad_norm": jnp.asarray(gnorm),
                   "overflow": jnp.asarray(overflow)}
        if self._fp16:
            metrics["loss_scale"] = jnp.asarray(scale)
        return metrics

    def _opt_to_host(self, opt):
        """Move optimizer state to pinned host DRAM (ZeRO-Offload residency)."""
        return jax.tree.map(
            lambda x, s: jax.device_put(x, s) if x is not None and s is not None
            else x,
            opt, self._opt_host_shardings, is_leaf=lambda x: x is None)

    def _opt_to_device(self, opt):
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(s.mesh, s.spec))
            if x is not None and s is not None else x,
            opt, self._opt_host_shardings, is_leaf=lambda x: x is None)

    def _activate_context(self):
        """Republish this engine's mesh/plan as the ambient parallel context
        (another Engine/InferenceEngine in the same process may have
        overwritten it)."""
        from deepspeed_tpu.parallel.context import set_parallel_context
        set_parallel_context(self.mesh, self.plan)

    def eval_batch(self, batch):
        self._activate_context()
        if self._infinity:
            return self._infinity_exec.eval_batch(batch)
        batch = self._device_batch(batch)
        with self.mesh:
            return self._eval_step(self.state, batch)

    def audit(self, batch=None, *, settings=None, raise_on_findings=False):
        """Static analysis of this engine's own compiled step programs
        (graft-lint, ``deepspeed_tpu/analysis``): lower the jitted steps on
        abstract shapes — nothing executes — and check the collective
        census, buffer donation, dtype promotion, and replication budget
        against this config's expectations.

        Reference analogue: none — DeepSpeed can only discover an extra
        allreduce by watching the wire (comms_logger); here the compiled
        program is inspected before a single step runs. Returns an
        ``analysis.Report``; with raise_on_findings=True, raises
        RuntimeError when any error-severity finding survives
        suppression/baseline."""
        self._activate_context()
        from deepspeed_tpu.analysis import audit_engine
        report = audit_engine(self, batch=batch, settings=settings)
        if raise_on_findings and not report.ok:
            raise RuntimeError("engine.audit found problems:\n"
                               + report.summary())
        return report

    # --- 3-call compatibility API (reference: forward:1652/backward:1794/step:1990)
    def forward(self, batch):
        """Compute loss+grads for one microbatch; grads are buffered until
        step(). Returns the (unscaled) loss."""
        if self._onebit_comm:
            raise RuntimeError(
                "the 3-call forward/backward/step API is not available with "
                "the 1-bit compressed path (grads must stay per-device local "
                "inside one compiled step) — use train_batch()")
        self._activate_context()
        self._rng, sub = jax.random.split(self._rng)
        batch = self._device_batch(batch)
        with self.mesh:
            loss, grads = self._grad_only(self.state, batch, sub)
        self._pending = (loss, grads)
        return loss

    def backward(self, loss=None):
        """Accumulate the pending grads (the jitted fwd already differentiated;
        this keeps the reference's call order meaningful)."""
        if getattr(self, "_pending", None) is None:
            raise RuntimeError("backward() called without forward()")
        loss, grads = self._pending
        self._pending = None
        with self.mesh:
            if self._grad_buffer is None:
                self._grad_buffer = grads
                self._loss_sum = loss
            else:
                self._grad_buffer = self._accum(self._grad_buffer, grads)
                self._loss_sum = self._loss_sum + loss
        self._accum_count += 1
        self.micro_steps += 1
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        # pp mode: the pipelined loss consumes all microbatches in one call
        needed = 1 if self._pp_mode else self.config.gradient_accumulation_steps
        return self._accum_count >= needed

    def step(self):
        """Apply the optimizer if at a grad-accum boundary (reference:
        is_gradient_accumulation_boundary:1875 + _take_model_step:1925)."""
        if not self.is_gradient_accumulation_boundary():
            return None
        mean_loss = self._loss_sum / self._accum_count
        if self._nvme_opt:
            gas = self.config.gradient_accumulation_steps
            grads = jax.tree.map(lambda g: g / gas, self._grad_buffer)
            metrics = self._nvme_apply(grads, mean_loss)  # accounts skips
            self._grad_buffer = None
            self._accum_count = 0
            self.global_steps += 1
            self._log_step(metrics)
            return metrics
        if self._offload_opt:
            self.state["opt"] = self._opt_to_device(self.state["opt"])
        with self.mesh:
            self.state, metrics = self._apply(
                self.state, self._grad_buffer, mean_loss)
        if self._offload_opt:
            self.state["opt"] = self._opt_to_host(self.state["opt"])
        self._grad_buffer = None
        self._accum_count = 0
        self.global_steps += 1
        # skip accounting is in-graph (state["skipped"]) — no overflow fetch
        self._log_step(metrics)
        return metrics

    # ------------------------------------------------------------------
    def _device_batch(self, batch):
        """Sharding-aware batch placement. IDEMPOTENT: a leaf already placed
        with the target sharding passes through untouched, so the
        PrefetchLoader can run this ahead of time and curriculum/LTD/PLD
        rewrites (which slice or extend the batch) are simply re-placed at
        consume time."""
        spec = self._batch_spec()
        def place(x, sh):
            if isinstance(x, jax.Array) and x.sharding == sh:
                return x  # already resident (prefetch path): no dispatch
            x = jnp.asarray(x) if not isinstance(x, jax.Array) else x
            return jax.device_put(x, sh)
        def put(x):
            x = jnp.asarray(x) if not isinstance(x, jax.Array) else x
            s = P(*spec[:min(x.ndim, len(spec))])  # 0-d leaves → replicated
            return place(x, NamedSharding(self.mesh, s))
        repl = NamedSharding(self.mesh, P())
        if isinstance(batch, dict):
            return {k: (place(jnp.asarray(v) if not isinstance(v, jax.Array)
                              else v, repl)
                        if _is_side_channel(k) else put(v))
                    for k, v in batch.items()}
        return jax.tree.map(put, batch)

    def _device_batches(self, stacked):
        """Place a K-stacked batch (leaves ``[K, global_batch, ...]``) for
        the fused multi-step program: the leading step dim is replicated
        (each unrolled step slices its own row), the rest shards exactly
        like _device_batch."""
        spec = self._batch_spec()
        def put(key, x):
            x = jnp.asarray(x) if not isinstance(x, jax.Array) else x
            if _is_side_channel(key) or x.ndim <= 1:
                s = P()  # replicated: [K] side-channels / scalars
            else:
                s = P(None, *spec[:min(x.ndim - 1, len(spec))])
            return jax.device_put(x, NamedSharding(self.mesh, s))
        if isinstance(stacked, dict):
            return {k: put(k, v) for k, v in stacked.items()}
        return jax.tree.map(lambda x: put(None, x), stacked)

    def _log_step(self, metrics):
        # keep the device array; get_global_grad_norm() fetches on demand
        if "grad_norm" in metrics:
            self._last_grad_norm = metrics["grad_norm"]
        cfg = self.config
        if self._tel_host is not None:
            # host-driven optimizer paths: queue the step's metric scalars
            # UN-fetched; the boundary drain below folds them in with the
            # same single device_get
            self._tel_host.add(metrics)
        # window-crossing check, not `% == 0`: a fused K-step dispatch
        # advances global_steps by K and can stride over the exact multiple
        window = self.global_steps // max(1, cfg.steps_per_print)
        if window == self._last_log_window:
            return
        self._last_log_window = window
        # the ONE steady-state sync point of the hot loop: every logged
        # metric AND the telemetry accumulator leaf come back in a single
        # device_get instead of one blocking float() per metric
        extra = {k: metrics[k] for k in ("loss", "grad_norm", "loss_scale")
                 if k in metrics}
        need_skipped = (self._schedule is not None
                        and isinstance(self.state, dict)
                        and "skipped" in self.state)
        if need_skipped:
            # the LR schedule evaluates at the applied-update count, which
            # needs the device skip counter — ride the same batched fetch
            # instead of a second round trip through get_lr()
            extra["_skipped"] = self.state["skipped"]
        tel_cur, fetched = self._fetch_telemetry(extra=extra)
        skipped_dev = fetched.pop("_skipped", None)
        vals = {k: float(np.asarray(v)) for k, v in fetched.items()}
        if self._schedule is not None:
            skipped = self._skipped_offset + (
                int(np.asarray(skipped_dev)) if skipped_dev is not None
                else self._device_skipped())
            lr = float(self._schedule(self.global_steps - skipped + 1))
        else:
            lr = self.get_lr()
        msg = (f"step={self.global_steps} loss={vals['loss']:.4f} "
               f"lr={lr:.3e} gnorm={vals.get('grad_norm', 0.0):.3f}")
        if "loss_scale" in vals:
            msg += f" scale={vals['loss_scale']:.0f}"
        logger.info(msg)
        events = [("Train/loss", vals["loss"], self.global_steps),
                  ("Train/lr", lr, self.global_steps)]
        if "grad_norm" in vals:
            events.append(("Train/grad_norm", vals["grad_norm"],
                           self.global_steps))
        if "loss_scale" in vals:
            events.append(("Train/loss_scale", vals["loss_scale"],
                           self.global_steps))
        records = []
        if self._tel_cfg is not None and tel_cur is not None:
            tel_events, records = self._drain_telemetry(tel_cur)
            events += tel_events
        from deepspeed_tpu.comm import comms_logger
        if comms_logger.enabled:
            # CommsLogger totals reach the monitor as comm/* events instead
            # of log-only text (trace-time counts/bytes + host_ms)
            events += comms_logger.events(self.global_steps)
        # robustness events (ckpt_fallback / fault_recovered / preempted /
        # fault_injected) ride the same window-boundary record stream
        from deepspeed_tpu.robustness import events as rb_events
        for rec in rb_events.drain():
            rec.setdefault("step", self.global_steps)
            records.append(rec)
        if self.monitor is not None and self.monitor.enabled:
            self.monitor.write_events(events)  # one batched write
            if records:
                self.monitor.write_records(records)

    # ------------------------------------------------------------------
    # telemetry plumbing (deepspeed_tpu/telemetry)
    # ------------------------------------------------------------------
    def _tel_anchor(self):
        """Anchor the first telemetry window AFTER the compile-bearing first
        dispatch so window rates aren't compile-polluted. One place — every
        dispatch path (dense/onebit/nvme, fused, infinity) calls it."""
        if self._tel_cfg is not None and self._tel_wall is None:
            self._tel_wall = time.perf_counter()
            self._tel_wall_steps = self.global_steps

    def _tel_span(self, name: str):
        """Tracer span when telemetry is on, else a no-op context."""
        return (self._tracer.span(name) if self._tracer is not None
                else contextlib.nullcontext())

    def _capture_static_args(self, fn, args, divisor: int):
        """Remember the jitted step + abstract arg shapes ONCE so the lazy
        static x runtime join can lower the same program off the hot path.
        Abstractify BEFORE dispatch: donation invalidates the state arrays."""
        if (self._tel_cfg is None or not self._tel_cfg.static_join
                or self._tel_abs is not None):
            return
        try:
            from deepspeed_tpu.analysis.program import abstractify
            self._tel_abs = (fn, abstractify(args), divisor)
        except Exception as e:  # noqa: BLE001 - telemetry never kills a run
            logger.debug(f"telemetry: static arg capture failed: {e!r}")
            self._tel_abs = ()   # falsy sentinel: don't retry every step

    def _tel_static_cost(self, wait: bool = False):
        """Cached per-step compiled costs (flops, modeled comm bytes) from
        the static join. The AOT lower+compile does NOT reuse the jit
        dispatch cache, so it runs in a daemon thread kicked off at the
        first window boundary — the training thread never stalls on it.
        Boundary drains poll (windows before it lands just lack the joined
        rates); an explicit drain_telemetry passes wait=True and joins."""
        if self._tel_static is not None:
            return self._tel_static or None
        if not self._tel_abs:
            return None
        if self._tel_static_thread is None:
            import threading

            def work():
                from deepspeed_tpu.telemetry import static_step_cost
                fn, abs_args, divisor = self._tel_abs
                cost = static_step_cost(fn, abs_args, mesh=self.mesh,
                                        divisor=divisor)
                with self._tel_lock:
                    self._tel_static = cost or {}

            self._tel_static_thread = threading.Thread(
                target=work, name="telemetry-static-join", daemon=True)
            self._tel_static_thread.start()
        if wait:
            self._tel_static_thread.join()
        elif self._tel_static_thread.is_alive():
            return None
        with self._tel_lock:
            if self._tel_static is None:  # worker died without a result
                self._tel_static = {}
        return self._tel_static or None

    def _fetch_telemetry(self, extra=None):
        """ONE batched device_get covering the caller's metric scalars, the
        in-graph accumulator leaf, and any pending host-window scalars.
        Returns (cumulative telemetry snapshot | None, fetched extras)."""
        fetch = dict(extra or {})
        if self._tel_in_graph and isinstance(self.state, dict) \
                and "telemetry" in self.state:
            fetch["_telemetry"] = self.state["telemetry"]
        if self._tel_host is not None:
            fetch["_tel_pending"] = self._tel_host.pending()
        fetched = jax.device_get(fetch)
        tel_cur = fetched.pop("_telemetry", None)
        pending = fetched.pop("_tel_pending", None)
        if self._tel_host is not None:
            tel_cur = self._tel_host.drain(pending)
        return tel_cur, fetched

    def _drain_telemetry(self, tel_cur, wait_static: bool = False):
        """Window statistics + events + structured records from one drained
        cumulative snapshot. Pure host work — the device fetch already
        happened in the caller's batched device_get."""
        from deepspeed_tpu.telemetry import joined_rates, window_stats
        now = time.perf_counter()
        wall = (now - self._tel_wall) if self._tel_wall is not None else None
        steps_in_window = self.global_steps - self._tel_wall_steps
        self._tel_wall, self._tel_wall_steps = now, self.global_steps
        win = window_stats(tel_cur, self._tel_prev)
        self._tel_prev = tel_cur
        if not (self._tel_in_graph and self._tel_cfg.update_ratio):
            # no ratio data on this path (disabled, or a host-driven
            # executor whose metrics carry no update norms) — a constant-0
            # series would read as "updates stopped"
            win.pop("update_ratio_mean", None)
            win.pop("update_ratio_max", None)
        if self._tracer is not None:
            win.update(self._tracer.drain_window())
            if "data_wait_ms" in win and "prefetch_ms" in win:
                # the prefetch device_put runs INSIDE the data_wait span
                # (PrefetchLoader tops up during next()); keep the nested
                # spans in the Chrome trace but un-double-count the window
                # total so data_wait_ms means "blocked on data, not placing"
                win["data_wait_ms"] = max(
                    0.0, win["data_wait_ms"] - win["prefetch_ms"])
            if win["steps"]:
                win["stall_ms_per_step"] = (win.get("block_ms", 0.0)
                                            / win["steps"])
        if wall and wall > 0 and steps_in_window > 0:
            win["wall_s"] = wall
            win["steps_per_sec"] = steps_in_window / wall
            static = self._tel_static_cost(wait=wait_static)
            if static is not None:
                from deepspeed_tpu.accelerator import get_accelerator
                accel = get_accelerator()
                peak = (accel.peak_flops_per_device("bf16")
                        * max(1, jax.device_count()))
                win.update(joined_rates(
                    static, win["steps_per_sec"], peak,
                    interconnect_bytes_per_sec=
                    accel.interconnect_bytes_per_sec()))
                if win.get("modeled_peak_hbm"):
                    # measured allocator high-water next to the static
                    # model (a cheap host call; 0 on transports that
                    # expose no memory_stats)
                    measured = accel.max_memory_allocated()
                    if measured:
                        win["measured_peak_hbm"] = float(measured)
        self._tel_last_window = win
        step = self.global_steps
        events = [(f"telemetry/{k}", float(win[k]), step)
                  for k in ("loss_mean", "loss_max", "gnorm_mean",
                            "gnorm_max", "overflow_rate",
                            "update_ratio_mean", "steps_per_sec",
                            "window_mfu", "modeled_comm_bytes_per_sec",
                            "exposed_comm_ms", "overlap_efficiency",
                            "modeled_peak_hbm", "measured_peak_hbm",
                            "stall_ms_per_step")
                  if win.get(k) is not None]
        records = [{"type": "telemetry_window", "step": step, **win}]
        if self._anomaly is not None:
            anomalies = self._anomaly.observe(win, step=step)
            for a in anomalies:
                logger.warning(f"anomaly[{a['severity']}] {a['rule']}: "
                               f"{a['message']}")
                if self._tracer is not None:
                    self._tracer.instant(f"anomaly:{a['rule']}",
                                         args={"severity": a["severity"]})
            # anomalies travel as records ONLY: scalar sinks get their
            # anomaly/<rule> projection from write_records (adding them to
            # `events` too would double-write every scalar sink)
            records += anomalies
        return events, records

    def drain_telemetry(self):
        """Force a window drain outside a steps_per_print boundary (one
        batched device fetch; events/records still fan out). Returns the
        window stats dict, or None when telemetry is off."""
        if self._tel_cfg is None:
            return None
        tel_cur, _ = self._fetch_telemetry()
        if tel_cur is None:
            return None
        events, records = self._drain_telemetry(tel_cur, wait_static=True)
        if self.monitor is not None and self.monitor.enabled:
            if events:
                self.monitor.write_events(events)
            if records:
                self.monitor.write_records(records)
        return self._tel_last_window

    def telemetry_window(self):
        """Last drained telemetry window stats (None before the first
        drain). Host dict — reading it costs nothing."""
        return self._tel_last_window

    def close(self, timeout: float = 5.0) -> bool:
        """Join background host threads with a bounded timeout. Today
        that is the telemetry static-join worker — daemon, so it never
        blocks interpreter exit, but a harness that builds many engines
        in one process wants the compile worker gone before the next
        engine starts. Returns False when the worker outlived the budget
        (its handle is kept so a later close can retry)."""
        t = self._tel_static_thread
        if t is not None and t.is_alive():
            t.join(timeout)
            if t.is_alive():
                return False
        self._tel_static_thread = None
        return True

    def export_trace(self, path: Optional[str] = None) -> str:
        """Write the host step-phase spans (dispatch/prefetch/data_wait/
        block) as Chrome-trace JSON loadable by chrome://tracing or
        Perfetto. Requires telemetry.enabled."""
        if self._tracer is None:
            raise RuntimeError("step tracing requires config "
                               '{"telemetry": {"enabled": true}}')
        if path is None:
            out = self.config.telemetry.trace.output_dir
            os.makedirs(out, exist_ok=True)
            path = os.path.join(out, f"step_trace_{self.global_steps}.json")
        return self._tracer.export_chrome_trace(path)

    # ------------------------------------------------------------------
    # info API (reference parity helpers)
    # ------------------------------------------------------------------
    def get_lr(self) -> float:
        if self._schedule is not None:
            # evaluate at the APPLIED update count (+1 = the lr the next
            # update will use); overflow-skipped steps don't advance it.
            # Plain Python int -> the schedule's numpy path: no device
            # program is built or run for a log-boundary call
            applied = self.global_steps - self.skipped_steps
            return float(self._schedule(applied + 1))
        if isinstance(self._base_lr, (int, float)):
            return float(self._base_lr)
        return 0.0

    @property
    def skipped_steps(self) -> int:
        """Overflow-skipped optimizer steps. The jitted paths account skips
        in-graph (state["skipped"]) so reading this is a LAZY device fetch —
        call it at steps_per_print boundaries, not per step; host-driven
        paths (NVMe swapper, layer-streamed executor) land on the host
        offset and cost nothing."""
        return self._skipped_offset + self._device_skipped()

    @skipped_steps.setter
    def skipped_steps(self, value: int):
        # checkpoint restore: reconcile the host offset against whatever the
        # (just-loaded) device counter says
        self._skipped_offset = int(value) - self._device_skipped()

    def _device_skipped(self) -> int:
        state = getattr(self, "state", None)
        if isinstance(state, dict) and "skipped" in state:
            return int(np.asarray(jax.device_get(state["skipped"])))
        return 0

    def get_loss_scale(self) -> float:
        if self._fp16:
            return float(self.state["loss_scale"]["scale"])
        return 1.0

    def get_global_grad_norm(self) -> Optional[float]:
        """Pre-clip global grad norm of the last applied step (reference:
        engine.get_global_grad_norm). None before the first step."""
        if self._last_grad_norm is None:
            return None
        return float(np.asarray(jax.device_get(self._last_grad_norm)))

    def sparse_gradients_enabled(self) -> bool:
        """API parity with the reference's sparse-embedding-grad switch
        (``engine.py:2302-2369`` sparse_allreduce_list). Always False on
        TPU — BY DESIGN, not omission: under jit+GSPMD the embedding
        cotangent is a fused scatter-add reduce-scattered over ICI like
        every other gradient (V*H/dp bytes/chip), a (values, indices)
        wire would need dynamic shapes, and the static-shape alternative
        moves more bytes at every realistic (vocab, batch). Evidence:
        ``benchmarks/embedding_grad.py``."""
        return False

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    def get_mesh(self) -> Mesh:
        return self.mesh

    @property
    def params(self):
        return self.state["params"]

    # ------------------------------------------------------------------
    # checkpointing (reference: save_checkpoint:2817 / load_checkpoint:2512)
    # ------------------------------------------------------------------
    def attach_dataloader(self, loader) -> None:
        """Register the training loader so checkpoints carry its position
        (epoch, batch-in-epoch, seed) and an elastic resume neither replays
        nor skips data. Any object with state_dict/load_state_dict works
        (DataLoader and RepeatingLoader both do)."""
        self._dataloader = loader

    def _rng_key_data(self):
        """Host uint32 view of the engine rng chain (typed or legacy key)."""
        key = self._rng
        try:
            key = jax.random.key_data(key)
        except Exception:  # noqa: BLE001 - already a legacy uint32 key
            pass
        return np.asarray(jax.device_get(key))

    def _restore_rng(self, key_data) -> None:
        arr = np.asarray(key_data, dtype=np.uint32)
        try:
            if jnp.issubdtype(self._rng.dtype, jax.dtypes.prng_key):
                impl = jax.random.key_impl(self._rng)
                self._rng = jax.random.wrap_key_data(jnp.asarray(arr),
                                                     impl=impl)
                return
        except Exception:  # noqa: BLE001 - legacy raw-key path below
            pass
        self._rng = jnp.asarray(arr)

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        save_latest: bool = True) -> str:
        tag = tag if tag is not None else f"global_step{self.global_steps}"
        client_state = dict(client_state or {})
        client_state.update({
            "global_steps": self.global_steps,
            "skipped_steps": self.skipped_steps,
            "micro_steps": self.micro_steps,
            # the rng split chain: restoring it makes replayed steps after a
            # fault recovery bit-identical to the uninterrupted run
            "rng_key": self._rng_key_data().tolist(),
        })
        if self._dataloader is not None and \
                hasattr(self._dataloader, "state_dict"):
            client_state.setdefault("data_position",
                                    self._dataloader.state_dict())
        if self._infinity:
            return self._save_infinity_checkpoint(save_dir, tag, client_state,
                                                  save_latest)
        engine = None
        if self.config.checkpoint.async_save:
            if self._ckpt_engine is None:
                self._ckpt_engine = ckpt_mod.OrbaxCheckpointEngine(async_save=True)
            engine = self._ckpt_engine  # .save() finalizes any in-flight save
        ck = self.config.checkpoint
        if self._nvme_opt:
            # fp32 optimizer chunks live on NVMe, not in self.state — persist
            # them alongside the Orbax state (reference: optimizer swap files
            # are re-read into the checkpoint, optimizer_utils.py). Written
            # BEFORE the save finalizes so the integrity manifest covers
            # them: a truncated optswap.npz must fail validation too.
            path = os.path.join(save_dir, str(tag))
            os.makedirs(path, exist_ok=True)
            np.savez(os.path.join(path, "optswap.npz"),
                     **self._swapper.export_state())
        return ckpt_mod.save_checkpoint(
            save_dir, tag, self.state, client_state=client_state,
            config_dict=self.config.to_dict(), save_latest=save_latest,
            engine=engine, write_integrity=ck.integrity,
            checksums=ck.integrity_checksums, keep_last_k=ck.keep_last_k)

    def wait_checkpoint(self):
        """Block until an in-flight async checkpoint is durable (and its
        `latest` pointer written). No-op when async_save is off."""
        if self._ckpt_engine is not None:
            self._ckpt_engine.wait()

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True):
        self.wait_checkpoint()
        if tag is not None:
            # an explicit tag is honored verbatim — the caller asked for
            # exactly that save, so a failure there must surface
            return self._load_resolved(load_dir, str(tag),
                                       load_optimizer_states,
                                       load_lr_scheduler_states)
        # tag=None: resolve + integrity-validate, then load; if a VALIDATED
        # tag still fails to load (shallow validation with checksums off, a
        # payload-format error), keep walking back — the elastic rebuild
        # must land on SOME loadable save while one exists
        tried = set()
        last_err = None
        while True:
            try:
                resolved, _fell_back = ckpt_mod.resolve_load_tag(
                    load_dir, exclude=tried)
            except FileNotFoundError:
                if last_err is not None:
                    raise last_err
                raise
            try:
                return self._load_resolved(load_dir, resolved,
                                           load_optimizer_states,
                                           load_lr_scheduler_states)
            except Exception as e:  # noqa: BLE001 - walk back on any failure
                tried.add(resolved)
                last_err = e
                logger.warning(f"checkpoint tag '{resolved}' validated but "
                               f"failed to load ({e!r}); walking back")
                from deepspeed_tpu.robustness import events as rb_events
                rb_events.emit("ckpt_fallback", dir=load_dir,
                               requested=resolved, resolved=None,
                               reason=f"load-error: {e}")

    def _load_resolved(self, load_dir: str, tag: str,
                       load_optimizer_states: bool,
                       load_lr_scheduler_states: bool):
        """Load one specific, already-resolved tag. Every sub-path (Orbax
        state, optional-leaf retries, optswap.npz, infinity) reads the SAME
        tag; the walk-back policy lives in load_checkpoint above."""
        if self._infinity:
            return self._load_infinity_checkpoint(load_dir, tag)
        try:
            state, client_state = ckpt_mod.load_checkpoint(
                load_dir, tag, template=self.state,
                shardings=self.state_shardings)
        except Exception as orig:
            optional = [k for k in ("skipped", "telemetry")
                        if isinstance(self.state, dict) and k in self.state]
            if not optional:
                raise
            # checkpoints written before the device-resident skip counter /
            # telemetry accumulators lack those leaves: retry without each
            # combination, rebuild the dropped leaves fresh (the
            # skipped_steps setter reconciles the host offset against
            # client_state below). If every retry fails, the failure wasn't
            # the missing leaves: surface the ORIGINAL error, not a retry's
            import itertools as _it
            state = None
            dropped = ()
            for r in range(1, len(optional) + 1):
                for drop in _it.combinations(optional, r):
                    tmpl = {k: v for k, v in self.state.items()
                            if k not in drop}
                    sh = {k: v for k, v in self.state_shardings.items()
                          if k not in drop}
                    try:
                        state, client_state = ckpt_mod.load_checkpoint(
                            load_dir, tag, template=tmpl, shardings=sh)
                        dropped = drop
                        break
                    except Exception:
                        continue
                if state is not None:
                    break
            if state is None:
                raise orig
            if "skipped" in dropped:
                state["skipped"] = jax.device_put(
                    jnp.zeros((), jnp.int32), self.state_shardings["skipped"])
            if "telemetry" in dropped:
                state["telemetry"] = jax.device_put(
                    tel_acc.init_leaf(
                        self.config.telemetry.gnorm_hist_buckets),
                    self.state_shardings["telemetry"])
        if not load_optimizer_states:
            state["opt"] = self.state["opt"]
        if self._offload_opt:
            state["opt"] = self._opt_to_host(state["opt"])
        if self._nvme_opt and load_optimizer_states:
            swap_file = os.path.join(load_dir, str(tag), "optswap.npz")
            with np.load(swap_file) as z:
                self._swapper.import_state({k: z[k] for k in z.files})
        self.state = state
        self.global_steps = int(client_state.get("global_steps", 0))
        self.skipped_steps = int(client_state.get("skipped_steps", 0))
        self.micro_steps = int(client_state.get("micro_steps", 0))
        if "rng_key" in client_state:
            self._restore_rng(client_state["rng_key"])
        if self._dataloader is not None and "data_position" in client_state \
                and hasattr(self._dataloader, "load_state_dict"):
            self._dataloader.load_state_dict(client_state["data_position"])
        # restored cumulative telemetry counters: restart the window diff
        # baseline so the first post-restore window isn't a cross-run delta
        self._tel_prev = None
        self._tel_wall = None
        self._tel_wall_steps = self.global_steps
        if self._onebit_comm:
            # phase selection must track the OPTIMIZER's applied count, which
            # resets when load_optimizer_states=False while global_steps
            # doesn't — re-sync the host mirror from device state
            self._onebit_applied = int(np.asarray(jax.device_get(
                self.state["opt"]["step"]))[0])
        return load_dir, client_state

    def _save_infinity_checkpoint(self, save_dir, tag, client_state,
                                  save_latest):
        """Infinity mode: chunk files are copied verbatim; the small
        HBM-resident (non-layer) state goes into an npz with a dtype
        manifest (the same bf16-as-uint16 scheme as save_16bit_model)."""
        path = os.path.join(save_dir, str(tag))
        os.makedirs(path, exist_ok=True)
        from deepspeed_tpu.robustness import integrity as rb_integrity
        rb_integrity.invalidate(path)  # in-place overwrite reads as torn
        small = self._infinity_exec.save_checkpoint(path)
        client_state["applied_steps"] = small.pop("applied_steps")
        if "loss_scale" in small:
            client_state["loss_scale"] = small.pop("loss_scale")
        flat = _flatten_dict({"nl_params": small["nl_params"],
                              "nl_opt": small["nl_opt"]})
        dtypes, arrays = {}, {}
        for key, arr in flat.items():
            arr = np.asarray(arr)
            dtypes[key] = str(arr.dtype)
            if "bfloat16" in str(arr.dtype):
                arr = arr.view(np.uint16)
            arrays[key.replace("/", "__")] = arr
        np.savez(os.path.join(path, "infinity_small.npz"), **arrays)
        with open(os.path.join(path, "infinity_meta.json"), "w") as f:
            json.dump({"dtypes": dtypes, "client_state": client_state}, f)
        ck = self.config.checkpoint
        ckpt_mod.finalize_tag(save_dir, tag, save_latest=save_latest,
                              write_integrity=ck.integrity,
                              checksums=ck.integrity_checksums,
                              keep_last_k=ck.keep_last_k)
        logger.info(f"saved infinity checkpoint {path}")
        return path

    def _load_infinity_checkpoint(self, load_dir, tag):
        import ml_dtypes
        if tag is None:
            tag, _fell_back = ckpt_mod.resolve_load_tag(load_dir)
        path = os.path.join(load_dir, str(tag))
        with open(os.path.join(path, "infinity_meta.json")) as f:
            meta = json.load(f)
        flat = {}
        with np.load(os.path.join(path, "infinity_small.npz")) as z:
            for k in z.files:
                key = k.replace("__", "/")
                arr = z[k]
                if "bfloat16" in meta["dtypes"][key]:
                    arr = arr.view(ml_dtypes.bfloat16)
                flat[key] = arr
        tree = _unflatten_dict(flat)
        client_state = meta["client_state"]
        small = {"nl_params": tree["nl_params"], "nl_opt": tree["nl_opt"],
                 "applied_steps": client_state.get("applied_steps", 0)}
        if "loss_scale" in client_state:
            small["loss_scale"] = client_state["loss_scale"]
        self._infinity_exec.load_checkpoint(path, small)
        self.global_steps = int(client_state.get("global_steps", 0))
        self.skipped_steps = int(client_state.get("skipped_steps", 0))
        self.micro_steps = int(client_state.get("micro_steps", 0))
        if "rng_key" in client_state:
            self._restore_rng(client_state["rng_key"])
        if self._dataloader is not None and "data_position" in client_state \
                and hasattr(self._dataloader, "load_state_dict"):
            self._dataloader.load_state_dict(client_state["data_position"])
        logger.info(f"loaded infinity checkpoint {path}")
        return load_dir, client_state

    def save_16bit_model(self, save_dir: str, name: str = "model_fp16.ckpt"):
        """Gathered 16-bit weights export (reference:
        _zero3_consolidated_16bit_state_dict:3146 / save_16bit_model:3213).

        bf16 has no native npz dtype, so bf16 arrays are stored as uint16
        views plus a dtype manifest; `load_16bit_model` restores them."""
        gathered = jax.tree.map(
            lambda p: np.asarray(jax.device_get(p)), self.state["params"])
        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, name)
        if not path.endswith(".npz"):
            path += ".npz"
        flat = _flatten_dict(gathered)
        dtypes = {}
        arrays = {}
        for key, arr in flat.items():
            dtypes[key] = str(arr.dtype)
            if arr.dtype.kind == "V" or "bfloat16" in str(arr.dtype):
                arr = arr.view(np.uint16)
            arrays[key] = arr
        np.savez(path, **arrays)
        with open(path + ".dtypes.json", "w") as f:
            json.dump(dtypes, f)
        return path


def load_16bit_model(path: str):
    """Restore a save_16bit_model export as {name: np.ndarray} (bf16 arrays
    come back as ml_dtypes.bfloat16)."""
    if not path.endswith(".npz"):
        path += ".npz"
    data = dict(np.load(path))
    manifest = path + ".dtypes.json"
    if os.path.exists(manifest):
        import ml_dtypes
        with open(manifest) as f:
            dtypes = json.load(f)
        for key, dt in dtypes.items():
            if "bfloat16" in dt and key in data:
                data[key] = data[key].view(ml_dtypes.bfloat16)
    return data


def _stack_batches(batches):
    """Stack K host batches on a new leading step dim for the fused
    program. Host-side np.stack by design: the fused path consumes raw
    loader output (one device_put moves the whole K-chunk)."""
    if isinstance(batches[0], dict):
        return {k: np.stack([np.asarray(b[k]) for b in batches])
                for k in batches[0]}
    return jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *batches)


def _flatten_dict(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}" if not prefix else f"{prefix}/{k}"
        if isinstance(v, dict):
            out.update(_flatten_dict(v, key))
        elif v is not None:
            out[key] = v
    return out


def _manual_batch_specs(batch):
    """Per-leaf shard_map in_specs for a batch tree entering a region that
    is manual over `data`: side-channels and scalars replicate, data rows
    shard. The ONE place the rule lives — the deferred-sync region and the
    1-bit step both consult it."""
    if batch is None:
        return P("data")
    if isinstance(batch, dict):
        return {k: (P() if _is_side_channel(k)
                    or getattr(v, "ndim", 0) < 1 else P("data"))
                for k, v in batch.items()}
    return jax.tree.map(
        lambda x: P("data") if getattr(x, "ndim", 0) >= 1 else P(), batch)


def _is_side_channel(key) -> bool:
    """Batch-dict keys starting with "_" are per-step side-channels
    (_pld_theta, _moq_bits): replicated across microbatches and devices —
    their leading dim (if any) is NOT the batch dim. The ONE place the
    convention lives; _accum_micro_grads, _device_batch and the 1-bit
    batch specs all consult it."""
    return isinstance(key, str) and key.startswith("_")


def _infinity_mode(config) -> bool:
    """Whether the config selects the ZeRO-Infinity layer-streamed executor.
    Round 5: EVERY enabled offload_param routes here — the executor is the
    one param-offload train path (mixed cpu/nvme tiers collapse onto the
    nvme store with the host param cache on top; see Engine.__init__)."""
    return config.zero_optimization.offload_param.enabled


def _unflatten_dict(flat):
    out = {}
    for key, v in flat.items():
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _same_structure(a, b) -> bool:
    try:
        return jax.tree.structure(a) == jax.tree.structure(b)
    except Exception:
        return False
