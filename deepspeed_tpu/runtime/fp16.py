"""Mixed precision: dynamic loss scaling for fp16.

Reference: ``deepspeed/runtime/fp16/loss_scaler.py:84`` (DynamicLossScaler:
scale *= 2 every `scale_window` good steps, scale /= 2 on overflow with
hysteresis, floor at min_scale) and the overflow check
(``runtime/utils.py:171`` CheckOverflow / ``stage3.py:1884`` _has_inf_or_nan).

TPU-native: the scaler is a small pytree carried in the train state, updated
inside the jitted step with `jnp.where` (no host sync — the reference does a
blocking allreduce MAX per step; here the overflow flag stays on device).
bf16 needs no scaling (engine skips this entirely).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray     # i32 scalar
    hysteresis: jnp.ndarray     # i32 scalar (remaining tolerated overflows)


def init_loss_scale(initial_scale_power: int = 16,
                    hysteresis: int = 2) -> LossScaleState:
    return LossScaleState(
        scale=jnp.asarray(2.0 ** initial_scale_power, jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
    )


def static_loss_scale(value: float) -> LossScaleState:
    return LossScaleState(scale=jnp.asarray(value, jnp.float32),
                          good_steps=jnp.zeros((), jnp.int32),
                          hysteresis=jnp.zeros((), jnp.int32))


def has_overflow(grads) -> jnp.ndarray:
    """True if any grad is non-finite (reference: _has_inf_or_nan)."""
    leaves = jax.tree.leaves(grads)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g.astype(jnp.float32)))) for g in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def update_loss_scale(state: LossScaleState, overflow: jnp.ndarray,
                      dynamic: bool = True, scale_window: int = 1000,
                      scale_factor: float = 2.0, min_scale: float = 1.0,
                      max_hysteresis: int = 2,
                      consecutive_hysteresis: bool = False) -> LossScaleState:
    if not dynamic:
        return state
    # overflow: consume hysteresis; shrink when exhausted (hysteresis is NOT
    # replenished by the shrink itself — reference update_scale keeps
    # cur_hysteresis at 1 after a shrink)
    shrink = jnp.logical_and(overflow, state.hysteresis <= 1)
    hys = jnp.where(jnp.logical_and(overflow, jnp.logical_not(shrink)),
                    state.hysteresis - 1, state.hysteresis)
    new_scale = jnp.where(
        shrink, jnp.maximum(state.scale / scale_factor, min_scale), state.scale)
    # growth on scale_window consecutive good steps
    good = jnp.where(overflow, 0, state.good_steps + 1)
    grow = good >= scale_window
    new_scale = jnp.where(grow, new_scale * scale_factor, new_scale)
    good = jnp.where(grow, 0, good)
    full = jnp.asarray(max_hysteresis, jnp.int32)
    if consecutive_hysteresis:
        # replenish on every overflow-free step (reference's opt-in mode)
        hys = jnp.where(overflow, hys, full)
    else:
        # reference default: replenish only at a scale-growth boundary
        hys = jnp.where(grow, full, hys)
    return LossScaleState(scale=new_scale, good_steps=good, hysteresis=hys)


def scale_loss(loss, state: LossScaleState):
    return loss * state.scale.astype(loss.dtype)


def unscale_grads(grads, state: LossScaleState):
    inv = 1.0 / state.scale
    return jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
