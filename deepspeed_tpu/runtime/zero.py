"""ZeRO stages as sharding rules.

Reference: the partitioned-tensor runtimes —
``runtime/zero/stage_1_and_2.py:89`` (DeepSpeedZeroOptimizer: flat bit16
buffers, grad bucketing + reduce-scatter, per-partition optimizer step,
all-gather of updated params) and ``runtime/zero/stage3.py:65`` +
``partition_parameters.py:516`` (param surgery, fetch/release coordinator).

TPU-native design — the whole mechanism becomes sharding specs:

  stage 0: params/grads/opt replicated over dp; grads psum'ed (plain DP).
  stage 1: optimizer state (fp32 master + moments) sharded over the dp axis.
           GSPMD partitions the optimizer update and all-gathers the updated
           params — exactly `step:1635` + `all_gather_dp_groups:1738`, chosen
           by the XLA SPMD partitioner instead of hand-written buckets.
  stage 2: + gradient accumulation buffers carry the same dp-sharded spec, so
           XLA reduce-scatters each microbatch's grads into a sharded buffer
           (`average_tensor:893`'s reduce-scatter, without the bucketing
           machinery — XLA's collective combiner does the bucketing).
  stage 3: params themselves are sharded over the `fsdp` axis (partitioning
           rules in parallel/partitioning.py); XLA inserts all-gather at each
           use site and frees the gathered buffer after — the
           fetch/release/prefetch coordinator (`partitioned_param_coordinator
           .py`) falls out of XLA liveness + latency-hiding scheduling.

Persistence thresholds (`stage3_param_persistence_threshold`) survive as
"small params stay replicated": the rules only shard tensors bigger than the
threshold.
"""

import dataclasses
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.config import ZeroConfig
from deepspeed_tpu.parallel.mesh import MeshPlan
from deepspeed_tpu.utils.logging import logger


def zero_param_spec(spec: P, shape: Tuple[int, ...], plan: MeshPlan,
                    zero_cfg: ZeroConfig) -> P:
    """Adjust a parameter's TP spec for the ZeRO stage.

    Stage 3 sharding itself is handled by the logical rules (fsdp axis); this
    applies the persistence threshold: small params revert to replicated,
    matching `stage3_param_persistence_threshold` semantics.
    """
    if zero_cfg.stage < 3 or plan.fsdp <= 1:
        return _divisible_spec(spec, shape, plan)
    numel = int(np.prod(shape)) if shape else 1
    if numel <= zero_cfg.stage3_param_persistence_threshold:
        spec = P(*[None if ax == "fsdp" or (isinstance(ax, tuple) and "fsdp" in ax)
                   else ax for ax in spec])
    return _divisible_spec(spec, shape, plan)


def _divisible_spec(spec: P, shape: Tuple[int, ...], plan: MeshPlan) -> P:
    """Drop axis assignments whose dim the mesh axis size does not divide
    (e.g. a conv's 3-channel output on an fsdp=8 mesh): such params stay
    replicated on that dim instead of failing sharding validation."""
    sizes = plan.axis_sizes()
    entries = _axis_entries(spec)
    changed = False
    for i, e in enumerate(entries):
        if not e or i >= len(shape):
            continue
        kept = []
        denom = 1
        for a in e:
            n = sizes.get(a, 1)
            if shape[i] % (denom * n) == 0:
                kept.append(a)
                denom *= n
        if len(kept) != len(e):
            entries[i] = tuple(kept)
            changed = True
    if not changed:
        return spec
    out = [tuple(e) if len(e) > 1 else (e[0] if e else None) for e in entries]
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def _axis_entries(spec: P):
    out = []
    for entry in spec:
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(entry))
        else:
            out.append((entry,))
    return out


def opt_state_spec(param_spec: P, shape: Tuple[int, ...], plan: MeshPlan,
                   zero_cfg: ZeroConfig, dp_axis: str = "data") -> P:
    """Sharding for per-param optimizer state (fp32 master, moments).

    Stage >= 1: additionally shard the largest dim that is (a) not already
    sharded and (b) divisible by the dp axis size, over `data`. This is the
    ZeRO-1 partition of optimizer state without touching param layout.
    Falls back to the param spec if nothing divides (tiny params stay
    replicated — same as the reference's padding-free small tensors living in
    one partition).
    """
    if zero_cfg.stage < 1 or plan.data <= 1:
        return param_spec
    entries = _axis_entries(param_spec)
    while len(entries) < len(shape):
        entries.append(())
    used = {a for e in entries for a in e}
    if dp_axis in used:
        return param_spec
    mesh_sizes = plan.axis_sizes()
    # size of each dim's shard after existing sharding
    best_dim, best_size = -1, 0
    for i, dim in enumerate(shape):
        denom = int(np.prod([mesh_sizes.get(a, 1) for a in entries[i]])) if entries[i] else 1
        local = dim // denom if denom and dim % denom == 0 else 0
        if local and local % plan.data == 0 and local > best_size:
            best_dim, best_size = i, local
    if best_dim < 0:
        return param_spec
    entries[best_dim] = entries[best_dim] + (dp_axis,)
    out = [tuple(e) if len(e) > 1 else (e[0] if e else None) for e in entries]
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def grad_spec(param_spec: P, shape: Tuple[int, ...], plan: MeshPlan,
              zero_cfg: ZeroConfig) -> P:
    """Sharding for gradient accumulation buffers.

    Stage >= 2: grads live dp-sharded (reduce-scatter semantics). We reuse the
    optimizer-state spec so grads land exactly where the optimizer will read
    them. Stage < 2: grads follow the params.
    """
    if zero_cfg.stage >= 2:
        return opt_state_spec(param_spec, shape, plan, zero_cfg)
    return param_spec


def tree_opt_spec(param_specs, shapes, plan: MeshPlan, zero_cfg: ZeroConfig):
    return jax.tree.map(
        lambda s, sh: opt_state_spec(s, sh, plan, zero_cfg),
        param_specs, shapes, is_leaf=lambda x: isinstance(x, P))


def tree_grad_spec(param_specs, shapes, plan: MeshPlan, zero_cfg: ZeroConfig):
    return jax.tree.map(
        lambda s, sh: grad_spec(s, sh, plan, zero_cfg),
        param_specs, shapes, is_leaf=lambda x: isinstance(x, P))


def describe(zero_cfg: ZeroConfig, plan: MeshPlan) -> str:
    return (f"ZeRO stage {zero_cfg.stage} | mesh {plan.describe()} | "
            f"params {'fsdp-sharded' if zero_cfg.stage >= 3 else 'replicated'}, "
            f"grads {'dp-sharded' if zero_cfg.stage >= 2 else 'replicated'}, "
            f"opt {'dp-sharded' if zero_cfg.stage >= 1 else 'replicated'}")
