"""ZeRO-Infinity layer-streamed training: params + optimizer state on NVMe.

Reference: ``runtime/swap_tensor/partitioned_param_swapper.py:35`` (fp16
params on NVMe, fetched per submodule), ``partitioned_optimizer_swapper.py:27``
and ``runtime/zero/stage3.py:1735`` (per-sub-group swap-in → step → swap-out).
The headline this enables is BASELINE.md metric #2: max trainable params per
chip scales with NVMe capacity instead of HBM (40B on one V100-32GB in the
reference's blog).

TPU-native re-design: instead of hooking a module tree with fetch/release
callbacks (the reference's PartitionedParameterCoordinator), the transformer's
homogeneous stacked-layer structure makes layer streaming a *driver loop*:

    forward:  embed (HBM) → for each layer: fetch params(i) → jitted layer
              forward (one compiled program serves every layer) → save x_i
    backward: CE head vjp (HBM) → for each layer reversed: fetch params(i) →
              jitted recompute-VJP (per-layer remat) → stage grads(i) to host
    update:   global grad norm (clip) → for each layer: fetch opt chunk(i) →
              jitted fused flat-AdamW → write back opt chunk + bf16 params

HBM residency is O(1 layer) of params/grads/opt-state plus the (small)
embedding/head and per-layer activation checkpoints; host DRAM stages the
flat grads (needed for the global-norm clip before any update); NVMe holds
the bf16 param chunks and fp32 (master, m, v) opt chunks. IO is overlapped
with compute by a prefetch thread (reads run one layer ahead; writes are
bounded write-behind). The optimizer state is lazily initialized: a missing
chunk means master = bf16 param upcast, m = v = 0, so the first step pays no
separate O(state) init write.

Storage layout per layer: one flat vector (the layer's leaves concatenated in
a fixed order, padded to the chunk size) — bf16 bits as uint16 for the param
file, (3, C) fp32 for the opt chunk. Layer grads come out of the VJP already
flat because the jitted layer functions take the flat vector and unflatten
inside.
"""

import dataclasses
import math
import os
import shutil
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger

_PLANES = 3  # master, exp_avg, exp_avg_sq


class LayerStore:
    """Per-layer chunk store: bf16 params as uint16 bits, fp32 (3, C)
    optimizer chunks.

    Backends:
      nvme   — AIO chunk files (the true ZeRO-Infinity tier; local-disk
               fast on a real TPU-VM where NVMe sits next to the chip)
      host   — numpy buffers in this process (tests; CPU)
      pinned — jax arrays in TPU-host pinned DRAM (the fast tier when the
               client process is remote from the TPU host, e.g. a relay:
               bytes move host<->HBM by local DMA and never cross the wire)
    """

    def __init__(self, path: Optional[str], n_layers: int, chunk_elems: int,
                 backend: str = "nvme", host_sharding=None, aio_config=None):
        self.n_layers = n_layers
        self.chunk = chunk_elems
        self.backend = backend
        self._host: Dict[str, Any] = {}
        # pinned backend: per-kind pinned_host shardings ({"param": ...,
        # "opt": ...}) — on a multi-device mesh each device pins only its
        # fsdp shard of the chunk
        self._host_sh = host_sharding
        self._aio_r = self._aio_w = None
        self._dir = None
        if backend == "nvme":
            if not path:
                raise ValueError("LayerStore(nvme) requires a path")
            self._dir = os.path.join(path, f"dstpu-infinity-{os.getpid()}")
            os.makedirs(self._dir, exist_ok=True)
            try:
                from deepspeed_tpu.ops.aio import (AIOHandle, aio_available,
                                                   report_fallback)
                if aio_available():
                    # separate handles: reads (prefetch) and writes
                    # (write-behind) each get their own ring, with
                    # independently-sized queue depths from the config
                    # `aio` section (read_queue_depth / write_queue_depth)
                    self._aio_r = AIOHandle.from_config(aio_config, "read")
                    self._aio_w = AIOHandle.from_config(aio_config, "write")
                else:  # pragma: no cover - no toolchain
                    # structured event (not just a log line): a capacity
                    # tier silently on synchronous numpy IO must be
                    # visible in the telemetry stream
                    report_fallback("infinity-layer-store")
            except Exception as e:  # pragma: no cover
                from deepspeed_tpu.ops.aio import report_fallback
                report_fallback("infinity-layer-store", reason=f"{e}")

    def _path(self, kind: str, i: int) -> str:
        return os.path.join(self._dir, f"{kind}_{i}.bin")

    def _key(self, kind: str, i: int) -> str:
        return f"{kind}_{i}"

    def _write(self, kind: str, i: int, arr):
        if self.backend == "pinned":
            # eager DMA into TPU-host pinned DRAM (async dispatch); the
            # handle is the storage
            sh = self._host_sh[kind] if isinstance(self._host_sh, dict) \
                else self._host_sh
            self._host[self._key(kind, i)] = jax.device_put(arr, sh)
        elif self.backend == "host":
            self._host[self._key(kind, i)] = np.ascontiguousarray(arr).copy()
        elif self._aio_w is not None:
            # AIOHandle.pwrite carries its own bounded retry + named error
            self._aio_w.pwrite(self._path(kind, i), arr)
        else:
            from deepspeed_tpu.robustness import faults as rb_faults
            from deepspeed_tpu.robustness.retry import retry_io
            path = self._path(kind, i)
            data = np.ascontiguousarray(arr)

            def do_write():
                rb_faults.io_seam("nvme_write", path)
                data.tofile(path)
            retry_io(do_write, what="layer-chunk write", path=path)

    def _read(self, kind: str, i: int, shape, dtype,
              out: Optional[np.ndarray] = None):
        if self.backend in ("host", "pinned"):
            got = self._host.get(self._key(kind, i))
            return None if got is None else got
        p = self._path(kind, i)
        if not os.path.exists(p):
            return None
        if self._aio_r is not None:
            return self._aio_r.pread(p, shape, dtype, out=out)
        from deepspeed_tpu.robustness import faults as rb_faults
        from deepspeed_tpu.robustness.retry import retry_io

        def do_read():
            rb_faults.io_seam("nvme_read", p)
            if out is not None:
                # staging-buffer path: read straight into the caller's
                # pinned buffer (no per-read allocation in the hot loop).
                # A short read (torn/truncated chunk) must raise like the
                # np.fromfile path does, never hand back a buffer whose
                # tail is the PREVIOUS chunk's bytes
                with open(p, "rb") as f:
                    got = f.readinto(memoryview(out).cast("B"))
                if got != out.nbytes:
                    raise OSError(
                        f"short read: {got} of {out.nbytes} bytes from {p}")
                return out
            return np.fromfile(p, dtype).reshape(shape)
        return retry_io(do_read, what="layer-chunk read", path=p)

    # params: uint16 (bf16 bits), shape (C,)
    def write_param(self, i: int, bits: np.ndarray):
        self._write("param", i, bits)

    def read_param(self, i: int, out=None) -> Optional[np.ndarray]:
        return self._read("param", i, (self.chunk,), np.uint16, out=out)

    # opt: fp32 (3, C)
    def write_opt(self, i: int, buf: np.ndarray):
        self._write("opt", i, buf)

    def read_opt(self, i: int, out=None) -> Optional[np.ndarray]:
        return self._read("opt", i, (_PLANES, self.chunk), np.float32, out=out)

    def save_to(self, dst: str):
        """Checkpoint: copy every chunk into dst. Same PR-6 ``retry_io``
        contract as the step-path IO: a transient EIO mid-copy retries with
        backoff instead of torching the save."""
        from deepspeed_tpu.robustness.retry import retry_io
        os.makedirs(dst, exist_ok=True)
        if self.backend in ("host", "pinned"):
            for k, v in self._host.items():
                p = os.path.join(dst, f"{k}.bin")
                arr = np.asarray(jax.device_get(v))
                retry_io(lambda arr=arr, p=p: arr.tofile(p),
                         what="layer-chunk checkpoint write", path=p)
            return
        for f in os.listdir(self._dir):
            src, out = os.path.join(self._dir, f), os.path.join(dst, f)
            retry_io(lambda src=src, out=out: shutil.copyfile(src, out),
                     what="layer-chunk checkpoint copy", path=out)

    def load_from(self, src: str, saved_chunk: Optional[int] = None):
        """Restore chunks. `saved_chunk` (from the shapes manifest) may
        differ from self.chunk when the fsdp degree changed between save and
        load — chunks are zero-padded past the real layer numel, so
        re-chunking is a truncate-or-pad of the pad region."""
        saved = saved_chunk or self.chunk

        def rechunk(plane):
            if saved == self.chunk:
                return plane
            if saved > self.chunk:
                return np.ascontiguousarray(plane[:self.chunk])
            return np.pad(plane, (0, self.chunk - saved))

        from deepspeed_tpu.robustness.retry import retry_io
        for f in os.listdir(src):
            if not f.endswith(".bin"):
                continue
            kind, i = f[:-4].rsplit("_", 1)
            dtype = np.uint16 if kind == "param" else np.float32
            p = os.path.join(src, f)
            arr = retry_io(lambda p=p, dtype=dtype: np.fromfile(p, dtype),
                           what="layer-chunk checkpoint read", path=p)
            if kind == "opt":
                arr = np.stack([rechunk(p)
                                for p in arr.reshape(_PLANES, saved)])
            else:
                arr = rechunk(arr)
            self._write(kind, int(i), arr)

    def close(self):
        if self._dir:
            shutil.rmtree(self._dir, ignore_errors=True)
            # idempotent (pid-keyed dir): a re-run close() must not rmtree
            # a successor store's live directory
            self._dir = None


class StagingRing:
    """Rotating host staging buffers with write-behind fencing.

    The native host-Adam sweep keeps three operations in flight — read
    chunk i+1, update chunk i, drain chunk i-1 — over ``nbufs`` fixed
    buffers. A buffer may still be draining (its write-behind future is
    live) when the sweep comes back around to it; ``acquire`` is the
    fence that waits that future out before handing the buffer back.
    ``slot`` is the raw, unfenced view — identity checks only. Handing a
    ``slot`` result to a writer is exactly the aliasing race the
    ``staging-buffer-alias`` corpus entry demonstrates.
    """

    def __init__(self, nbufs: int, shape, dtype=np.float32):
        self.nbufs = nbufs
        self._bufs = [np.empty(shape, dtype) for _ in range(nbufs)]
        self._busy: list = [None] * nbufs

    def slot(self, i: int) -> np.ndarray:
        """Raw buffer for slot ``i % nbufs`` — no fence, no wait."""
        return self._bufs[i % self.nbufs]

    def acquire(self, i: int) -> np.ndarray:
        """Buffer for slot ``i % nbufs`` after its drain (if any) lands."""
        k = i % self.nbufs
        busy = self._busy[k]
        if busy is not None:
            busy.result()
            self._busy[k] = None
        return self._bufs[k]

    def mark_busy(self, i: int, fut) -> None:
        """Record the write-behind future draining slot ``i % nbufs``."""
        self._busy[i % self.nbufs] = fut

    def drain(self) -> None:
        """Wait out every live write-behind."""
        for k, busy in enumerate(self._busy):
            if busy is not None:
                busy.result()
                self._busy[k] = None


class InfinityExecutor:
    """Layer-streamed train/eval over NVMe-resident transformer layers.

    Owns: the LayerStore, the per-layer jitted programs, the non-layer
    (embed/head/norm) params + their optimizer, the prefetch/write pools.
    The engine delegates train_batch/eval_batch/checkpoint to this object
    when ``offload_param.device == "nvme"``.
    """

    def __init__(self, model_cfg, *, rng, nvme_path: str,
                 lr=1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w_mode: bool = True,
                 bias_correction: bool = True, grad_clip: float = 0.0,
                 backend: str = "nvme", param_cache_bytes: int = 0,
                 gas: int = 1, mesh=None, fp16: Optional[Dict[str, Any]] = None,
                 compression=None, use_cpu_adam: bool = False,
                 max_live_params: int = 0, moq: bool = False,
                 pipeline: bool = True, aio_config=None):
        if model_cfg.num_experts > 1:
            raise ValueError("offload_param.device=nvme supports dense "
                             "transformers (MoE experts not yet streamed)")
        if model_cfg.attn_windows:
            raise ValueError("layer-streamed offload does not thread "
                             "per-layer attn_windows yet (one jit serves "
                             "every layer)")
        self.cfg = dataclasses.replace(model_cfg, scan_layers=False,
                                       offload_params=False)
        self.b1, self.b2 = betas
        self.eps = eps
        self.wd = weight_decay
        self.awm = adam_w_mode
        self.bc = bias_correction
        self.lr = lr
        self.clip = grad_clip
        self.gas = gas
        self.applied_steps = 0
        # fp16 dynamic loss scaling, host-side (reference: the loss-scaler
        # state the fp16 optimizers carry, runtime/fp16/loss_scaler.py:84).
        # Storage bits stay bf16; compute runs in cfg.dtype (fp16), the
        # fp32 master in the opt chunk carries the precision.
        self.fp16 = dict(fp16) if fp16 else None
        if self.fp16:
            static = float(self.fp16.get("loss_scale", 0.0) or 0.0)
            self._dynamic_scale = static == 0.0     # reference: 0 = dynamic
            self._scale = (static if not self._dynamic_scale else
                           float(2.0 ** self.fp16.get("initial_scale_power",
                                                      16)))
            self._scale_window = int(self.fp16.get("loss_scale_window", 1000))
            self._min_scale = float(self.fp16.get("min_loss_scale", 1.0))
            self._hysteresis = int(self.fp16.get("hysteresis", 2))
            self._good_steps = 0
            self._hyst_left = self._hysteresis
        # compression transform applied to each streamed layer's params
        # (path-compatible with the monolithic engine path: the per-layer
        # tree is wrapped under "layers/", masks computed per layer)
        self.compression = compression
        # MoQ composes with layer streaming: each per-layer jit takes the
        # layer's scheduled bit-width as a traced scalar (the engine's
        # [L] ``_moq_bits`` side-channel, indexed per layer), so schedule
        # updates never recompile and the quantize-dequantize runs inside
        # the same program that unflattens the streamed chunk
        self.moq = bool(moq)

        L = self.cfg.num_layers
        # per-layer leaf template from a single-layer config (shapes only)
        cfg1 = dataclasses.replace(self.cfg, num_layers=1)
        from deepspeed_tpu.models.transformer import init_params
        shapes1 = jax.eval_shape(lambda k: init_params(k, cfg1),
                                 jax.random.PRNGKey(0))["layers"]
        self._leaves, self._treedef = jax.tree.flatten(shapes1)
        self._shapes = [l.shape[1:] for l in self._leaves]   # drop L=1 dim
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        numel = sum(self._sizes)
        self._pinned = backend == "pinned"

        # --- host-resident optimizer (ZeRO-Offload's compute design: the
        # fp32 master/m/v never cross the host<->HBM bus; reference:
        # csrc/adam/cpu_adam.cpp:21). Two TPU-native flavors:
        #   "xla_host" (pinned backend): the Adam sweep runs ON the TPU
        #     host's CPUs inside the XLA program via
        #     jax.experimental.compute_on("device_host") — opt chunks stay
        #     in pinned_host memory end to end, and per step only bf16
        #     grads cross down (params were already streaming for fwd/bwd).
        #   "native" (host/nvme backends, i.e. this process IS the TPU
        #     host): the fused C++ AdamW (csrc/adam/dstpu_cpu_adam.cpp)
        #     updates the store's chunks in place.
        self._host_adam = None
        if use_cpu_adam:
            if self._pinned:
                self._host_adam = "xla_host"
            else:
                from deepspeed_tpu.ops.cpu_adam import cpu_adam_available
                if cpu_adam_available():
                    self._host_adam = "native"
                else:  # pragma: no cover - toolchain missing
                    logger.warning("use_cpu_adam requested but the native "
                                   "library failed to build; optimizer "
                                   "chunks will round-trip through HBM")

        # --- mesh: offload composes with data/fsdp parallelism (reference:
        # ZeRO-3 + NVMe at 512 GPUs, stage3.py:65 + partitioned_param_
        # swapper.py:35). Layer chunks shard over `fsdp` (each device stages
        # only its shard; one all-gather on use = the ZeRO-3 fetch); batch
        # shards over (data, fsdp); grads reduce-scatter back to `fsdp`; the
        # fused Adam sweep is fully shard-local.
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        if mesh is not None and mesh.size > 1:
            self.mesh = mesh
        else:
            dev = mesh.devices.flat[0] if mesh is not None else jax.devices()[0]
            self.mesh = Mesh(np.asarray([dev]).reshape(1, 1),
                             ("data", "fsdp"))
        mesh_shape = dict(self.mesh.shape)
        for ax in ("pipe", "seq", "expert"):
            if mesh_shape.get(ax, 1) > 1:
                raise ValueError(f"layer-streamed offload shards over "
                                 f"data/fsdp/tensor; mesh axis '{ax}' > 1")
        self._F = mesh_shape.get("fsdp", 1)
        self._TP = mesh_shape.get("tensor", 1)
        self.dp = self._F * mesh_shape.get("data", 1)
        self._batch_axes = tuple(a for a in ("data", "fsdp")
                                 if a in mesh_shape)
        single = self.mesh.size == 1
        # flat chunks shard over fsdp AND tensor (pure storage
        # distribution); the TP leaf constraints in the layer jits are
        # what turn the tensor axis into Megatron-style compute sharding
        # (reference: ZeRO-3+NVMe under a Megatron mpu,
        # runtime/engine.py:1088-1100 + zero/stage3.py:65)
        chunk_axes = (("fsdp", "tensor") if self._TP > 1 and self._F > 1
                      else ("tensor",) if self._TP > 1 else ("fsdp",))
        # on a 1-device mesh trivially-sharded specs are semantically P(),
        # but the sharded annotation routes pinned<->HBM device_put through
        # a slower path (measured 2.5x on the capacity rung) — use plain P()
        self._x_spec = P() if single else P(self._batch_axes)
        self._bits_spec = P() if single else P(chunk_axes)
        self._opt_spec = P() if single else P(None, chunk_axes)
        # per-leaf tensor-parallel specs for the unflattened layer tree
        # (col/row rules from parallel/partitioning; the leading "layers"
        # logical dim is dropped — the per-layer tree has no L axis)
        self._tp_leaf_specs = None
        if self._TP > 1:
            from deepspeed_tpu.models.transformer import (
                logical_axes as _logical_axes)
            from deepspeed_tpu.parallel.partitioning import (
                make_rules as _make_rules, spec_tree as _spec_tree)
            lay_axes = _logical_axes(self.cfg)["layers"]
            per_layer = jax.tree.map(
                lambda a: a[1:] if isinstance(a, tuple) else a, lay_axes,
                is_leaf=lambda x: x is None or isinstance(x, tuple))
            tp_tree = _spec_tree(per_layer, _make_rules(0, tp=True))
            self._tp_leaf_specs = jax.tree.flatten(
                tp_tree, is_leaf=lambda x: isinstance(x, P))[0]
        # memory_kind="device" is load-bearing: a device_put from a
        # pinned_host source with no explicit kind can keep the array on the
        # host tier, and every downstream jit then reads over PCIe. Some
        # CPU jaxlibs expose no device/pinned_host kinds at all (only
        # unpinned_host) — there the host tier is numpy buffers and the
        # un-kinded sharding means the same thing, so degrade to it rather
        # than failing construction.
        _degraded_kinds = set()

        def _kinded(spec, kind):
            try:
                return NamedSharding(self.mesh, spec, memory_kind=kind)
            except (ValueError, TypeError) as e:
                if kind not in _degraded_kinds:
                    _degraded_kinds.add(kind)
                    logger.warning(
                        f"memory_kind='{kind}' unsupported on this backend "
                        f"({e}); using un-kinded shardings — on real TPU "
                        "hardware this would defeat the host/HBM tiering, "
                        "on CPU jaxlibs there is no tiering to defeat")
                return NamedSharding(self.mesh, spec)

        self._x_sh = _kinded(self._x_spec, "device")
        self._bits_dev_sh = _kinded(self._bits_spec, "device")
        self._opt_dev_sh = _kinded(self._opt_spec, "device")
        self._repl_dev_sh = _kinded(P(), "device")
        self._bits_host_sh = _kinded(self._bits_spec, "pinned_host")
        self._opt_host_sh = _kinded(self._opt_spec, "pinned_host")
        self._repl_host_sh = _kinded(P(), "pinned_host")

        # chunk rounded so every fsdp x tensor shard is lane-aligned
        align = 128 * self._F * self._TP
        self.chunk = ((numel + align - 1) // align) * align
        self.layer_params = numel
        self.num_params = L * numel
        self.store = LayerStore(nvme_path, L, self.chunk, backend=backend,
                                host_sharding={"param": self._bits_host_sh,
                                               "opt": self._opt_host_sh},
                                aio_config=aio_config)
        # --- overlapped offload pipeline (reference: the three-stage
        # pipelined optimizer swapper, pipelined_optimizer_swapper.py:50).
        # pipeline=True (default): fwd/bwd walks keep TWO param fetches in
        # flight ahead of compute, and every update sweep runs the
        # three-way schedule  read(i+1) || update(i) || write(i-1)  with
        # SEPARATE read/write pools (a queued write-behind must never delay
        # the next prefetch behind it) and write-behind bounded to 2.
        # pipeline=False is the fully-drained executor: synchronous
        # resolve-at-use reads and a drain after every layer's write — the
        # `offload-serial-pipeline` corpus twin and the bit-for-bit
        # pipeline-bisection baseline.
        self.pipeline = bool(pipeline)
        self._rpool = ThreadPoolExecutor(max_workers=2)
        self._wpool = ThreadPoolExecutor(max_workers=2)
        self._pending_writes: list = []
        # host staging buffers, lazily allocated on first use: two per
        # plane (param bits / opt planes) for the double-buffered reads of
        # the device-Adam sweep, three opt buffers for the native host-Adam
        # sweep (read fills one while Adam updates another in place and
        # write-behind drains the third)
        self._opt_stage = None
        # host bf16-bits cache of param chunks (fast refetch for bwd/next
        # step; NVMe stays the system of record). Pointless for the pinned
        # backend — the store itself IS host memory.
        if self._pinned:
            self._cache_layers = 0
        else:
            self._cache_layers = param_cache_bytes // (2 * self.chunk) \
                if param_cache_bytes else L
        self._param_cache: Dict[int, np.ndarray] = {}
        # HBM-resident bits cache (reference: stage3_max_live_parameters —
        # params kept live in device memory, stage3.py's max_live knob).
        # Layers whose bf16 bits fit under the budget skip the fwd/bwd
        # re-fetch DMA entirely; the update refreshes cached entries.
        self._hbm_cache: Dict[int, Any] = {}
        self._hbm_cache_layers = (int(max_live_params) // max(1, numel)
                                  if max_live_params else 0)
        if self._hbm_cache_layers:
            logger.info(
                f"param live-cache: up to {min(self._hbm_cache_layers, L)} "
                f"of {L} layers resident in device memory "
                f"({max_live_params/1e9:.2f}B param budget)")

        self._build_jits()
        self._init_params(rng)
        tier = {"xla_host": ", Adam on the TPU host (compute_on; opt state "
                            "never crosses the host<->HBM bus)",
                "native": ", Adam in the native host kernel (opt state "
                          "never touches the device)"}.get(self._host_adam, "")
        logger.info(
            f"ZeRO-Infinity layer streaming: {L} layers x "
            f"{numel/1e6:.1f}M params on {backend} "
            f"({self.num_params/1e9:.2f}B layer params total, chunk "
            f"{self.chunk*2/1e6:.0f}MB bf16 + {self.chunk*12/1e6:.0f}MB opt)"
            f"{tier}")

    # ------------------------------------------------------------------
    def _adam_math(self, master, m, v, g, lr_t, step):
        """The one AdamW core every variant (device chunk, host chunk,
        embed/head device, embed/head host) traces: returns (master', m',
        v'). ``g`` arrives already scaled by the clip/scale coefficient."""
        from deepspeed_tpu.ops.adam import fused_adam_update
        return fused_adam_update(master, m, v, g, lr_t, step,
                                 b1=self.b1, b2=self.b2, eps=self.eps,
                                 wd=self.wd, awm=self.awm, bc=self.bc)

    # ------------------------------------------------------------------
    def _build_jits(self):
        cfg = self.cfg
        sizes, shapes = self._sizes, self._shapes
        treedef = self._treedef
        chunk = self.chunk
        b1, b2, eps = self.b1, self.b2, self.eps
        wd, awm, bc = self.wd, self.awm, self.bc
        multi = self.mesh.size > 1
        x_spec, bits_spec, opt_spec = (self._x_spec, self._bits_spec,
                                       self._opt_spec)
        from jax.sharding import PartitionSpec as P
        from deepspeed_tpu.models.transformer import (
            _norm, transformer_layer, chunked_cross_entropy)

        def wsc(t, spec):
            # constraints are what make the multi-device program ZeRO-3:
            # bits replicate (one all-gather) at use, grads land fsdp-sharded
            # (reduce-scatter), activations stay batch-sharded
            return jax.lax.with_sharding_constraint(t, spec) if multi else t

        compression = self.compression
        moq_on = self.moq

        tp_specs = self._tp_leaf_specs

        def leaves_from_flat(flat, step=None, qbits=None):
            """Gathered flat vector -> layer param pytree (compute dtype).
            The ONE place that slices/reshapes/TP-constrains leaves — used
            by both the forward unflatten and the backward fp32 view."""
            out, off = [], 0
            for j, (size, shape) in enumerate(zip(sizes, shapes)):
                leaf = jax.lax.dynamic_slice_in_dim(flat, off, size) \
                    .reshape(shape).astype(cfg.dtype)
                if tp_specs is not None:
                    # Megatron col/row sharding of the reshaped weight —
                    # this is what makes the tensor axis COMPUTE, not just
                    # storage: GSPMD partitions each matmul and inserts
                    # the psum on the row-parallel outputs
                    leaf = wsc(leaf, tp_specs[j])
                out.append(leaf)
                off += size
            tree = jax.tree.unflatten(treedef, out)
            if compression is not None:
                # same leaf paths as the monolithic engine path sees
                # ("layers/<name>"); masks are per-layer here
                tree = compression.apply(
                    {"layers": tree},
                    step if step is not None else 0)["layers"]
            if moq_on and qbits is not None:
                # MoQ fake-quant at this layer's scheduled bit-width;
                # weight leaves only (matches MoQ.apply's stacked ndim>=3
                # filter — per-layer norm scales/biases are 1-d)
                from deepspeed_tpu.runtime.quantize import (
                    _ste_quant_traced_bits)
                tree = {k: (_ste_quant_traced_bits(v, qbits)
                            if getattr(v, "ndim", 0) >= 2 else v)
                        for k, v in tree.items()}
            return tree

        def unflatten(flat_bits, step=None, qbits=None):
            """uint16 bf16-bits (C,) -> layer param pytree (compute dtype)."""
            flat = jax.lax.bitcast_convert_type(flat_bits, jnp.bfloat16)
            # one explicit all-gather of the bf16 chunk (the ZeRO-3 fetch);
            # without it every dynamic_slice below would gather separately
            flat = wsc(flat, P())
            return leaves_from_flat(flat, step, qbits)

        def layer_fwd(flat_bits, x, mask, positions, step, qbits):
            p = unflatten(flat_bits, step, qbits)
            y, _aux = transformer_layer(x, p, cfg, mask=mask,
                                        positions=positions,
                                        deterministic=True)
            return wsc(y, x_spec)

        self._layer_fwd = jax.jit(layer_fwd)

        def layer_bwd(flat_bits, x, dy, mask, positions, step, qbits):
            """Recompute-VJP for one layer: returns (flat fp32 grads, dx,
            grad sq-norm). The fwd recompute inside vjp IS the remat."""
            def f(bits_f32, x):
                # differentiate w.r.t. a fp32 VIEW of the params so the
                # cotangent comes back fp32 (bitcast isn't differentiable)
                p = leaves_from_flat(bits_f32, step, qbits)
                y, _aux = transformer_layer(x, p, cfg, mask=mask,
                                            positions=positions,
                                            deterministic=True)
                return y
            flat32 = wsc(jax.lax.bitcast_convert_type(
                flat_bits, jnp.bfloat16), P()).astype(jnp.float32)
            _, vjp = jax.vjp(f, flat32, x)
            dp, dx = vjp(dy)
            # batch-sum cotangent reduce-scatters onto the fsdp shards
            dp = wsc(dp, bits_spec)
            dx = wsc(dx, x_spec)
            return dp, dx, jnp.sum(dp.astype(jnp.float32) ** 2)

        self._layer_bwd = jax.jit(layer_bwd)

        def embed_fwd(nl, ids):
            x = nl["tok_embed"][ids].astype(cfg.dtype)
            if cfg.position_type == "learned":
                S = ids.shape[1]
                x = x + nl["pos_embed"][jnp.arange(S)[None]].astype(cfg.dtype)
            if cfg.embed_norm:
                x = _norm(x, nl["embed_norm_scale"],
                          nl.get("embed_norm_bias"), cfg)
            return wsc(x, x_spec)

        def top_loss(nl, x, labels):
            h = _norm(x, nl["final_norm_scale"], nl.get("final_norm_bias"),
                      cfg)
            head = nl.get("lm_head")
            tied = head is None
            if tied:
                head = nl["tok_embed"]
            c = cfg.loss_chunk if cfg.loss_chunk else min(1024, x.shape[1])
            return chunked_cross_entropy(h, head, labels, c, tied_embed=tied)

        def top_fwd_bwd(nl, x, labels, scale):
            def scaled(nl, x):
                return top_loss(nl, x, labels) * scale
            (loss, (dnl, dx)) = jax.value_and_grad(
                scaled, argnums=(0, 1))(nl, x)
            return loss, dnl, wsc(dx, x_spec)

        self._top_fwd_bwd = jax.jit(top_fwd_bwd)
        self._top_loss = jax.jit(top_loss)
        self._embed_fwd = jax.jit(embed_fwd)

        def embed_bwd(nl, ids, dx0):
            _, vjp = jax.vjp(lambda nl: embed_fwd(nl, ids), nl)
            (dnl,) = vjp(dx0)
            return dnl

        self._embed_bwd = jax.jit(embed_bwd)

        def tree_add(a, b):
            return jax.tree.map(jnp.add, a, b)

        self._tree_add = jax.jit(tree_add)
        self._scalar_add = jax.jit(lambda a, b: a + b)
        self._sq = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32) ** 2))
        self._nl_sq = jax.jit(
            lambda t, inv: sum(jnp.sum((l.astype(jnp.float32) * inv) ** 2)
                               for l in jax.tree.leaves(t)))

        adam_math = self._adam_math

        def adam_chunk(opt_buf, grad, param_bits, have_opt, lr_t, step,
                      coef):
            """Fused flat AdamW on one layer chunk. have_opt=False -> lazy
            init (master from the bf16 params, m = v = 0). grad: fp32, or
            bf16 bits as uint16 (the host-Adam wire dtype)."""
            p32 = jax.lax.bitcast_convert_type(
                param_bits, jnp.bfloat16).astype(jnp.float32)
            master = jnp.where(have_opt, opt_buf[0], p32)
            m = jnp.where(have_opt, opt_buf[1], 0.0)
            v = jnp.where(have_opt, opt_buf[2], 0.0)
            if grad.dtype == jnp.uint16:
                grad = jax.lax.bitcast_convert_type(
                    grad, jnp.bfloat16).astype(jnp.float32)
            master, m, v = adam_math(master, m, v, grad * coef, lr_t, step)
            new_bits = jax.lax.bitcast_convert_type(
                master.astype(jnp.bfloat16), jnp.uint16)
            return jnp.stack([master, m, v]), new_bits

        self._adam_chunk = jax.jit(adam_chunk, donate_argnums=(0,))
        # lazily-initialized opt chunk, born with the right fsdp sharding
        self._zeros_opt = jax.jit(
            lambda: jnp.zeros((_PLANES, chunk), jnp.float32),
            out_shardings=self._opt_dev_sh)

        if self._host_adam == "xla_host":
            # the same math compiled INTO the host memory space: opt chunks
            # live (and stay) in pinned_host; the sweep runs on the TPU
            # host's cores; only the fence scalar lands in device memory.
            # `have` is STATIC (two compiled variants): a traced
            # jnp.where(have, ...) would select between host-space planes
            # and default-space constants, which XLA rejects inside a
            # compute_on region.
            from jax.experimental.compute_on import compute_on

            def adam_chunk_host(opt_buf, grad_bits, param_bits, lr_t, step,
                                coef, have):
                @compute_on("device_host")
                @jax.jit
                def upd(opt_buf, grad_bits, param_bits, lr_t, step, coef):
                    flat = jax.lax.bitcast_convert_type(grad_bits,
                                                        jnp.bfloat16)
                    g = flat.astype(jnp.float32) * coef
                    if have:
                        master, m, v = opt_buf[0], opt_buf[1], opt_buf[2]
                    else:
                        master = jax.lax.bitcast_convert_type(
                            param_bits, jnp.bfloat16).astype(jnp.float32)
                        # derive zeros from the host array: a fresh
                        # jnp.zeros constant would be default-space
                        m = master * 0.0
                        v = master * 0.0
                    master, m, v = adam_math(master, m, v, g, lr_t, step)
                    new_bits = jax.lax.bitcast_convert_type(
                        master.astype(jnp.bfloat16), jnp.uint16)
                    return jnp.stack([master, m, v]), new_bits, master[0]
                return upd(opt_buf, grad_bits, param_bits, lr_t, step, coef)

            # scalars must enter host space too — a device-space scalar
            # poisons every elementwise op it touches with the default space
            scalar = (self._repl_host_sh,) * 3
            self._adam_chunk_host = jax.jit(
                adam_chunk_host,
                in_shardings=(self._opt_host_sh, self._bits_host_sh,
                              self._bits_host_sh) + scalar,
                out_shardings=(self._opt_host_sh, self._bits_host_sh,
                               self._repl_dev_sh),
                donate_argnums=(0,), static_argnums=(6,))
            self._zeros_opt_host = jax.jit(
                lambda: jnp.zeros((_PLANES, chunk), jnp.float32),
                out_shardings=self._opt_host_sh)
            # device-side grad -> bf16-bits cast (halves the staging DMA)
            self._grad_bits = jax.jit(
                lambda g: jax.lax.bitcast_convert_type(
                    g.astype(jnp.bfloat16), jnp.uint16))

    # ------------------------------------------------------------------
    def _init_params(self, rng):
        """Streamed init: one layer at a time (the full tree never exists)."""
        cfg = self.cfg
        L = cfg.num_layers
        from deepspeed_tpu.models.transformer import init_params
        cfg1 = dataclasses.replace(cfg, num_layers=1)
        # init_params scales residual-out weights by 1/sqrt(2*num_layers);
        # with a num_layers=1 config the draw comes out sqrt(L) too large
        rescale = 1.0 / math.sqrt(L)
        out_keys = ("wo", "w_out", "moe_w_out")
        sizes, shapes = self._sizes, self._shapes

        def one_layer(key):
            tree = init_params(key, cfg1)["layers"]
            tree = {k: (v * rescale if k in out_keys else v)
                    for k, v in tree.items()}
            flat = jnp.concatenate([
                jnp.reshape(v, (-1,)) for v in jax.tree.leaves(tree)
            ]).astype(jnp.bfloat16)
            flat = jnp.pad(flat, (0, self.chunk - flat.shape[0]))
            return jax.lax.bitcast_convert_type(flat, jnp.uint16)

        one_layer = jax.jit(one_layer, out_shardings=self._bits_dev_sh)
        keys = jax.random.split(jax.random.fold_in(rng, 17), L + 1)
        for i in range(L):
            bits = one_layer(keys[i])
            if self._pinned:
                self.store.write_param(i, bits)  # device->pinned_host DMA
            else:
                self.store.write_param(i, np.asarray(jax.device_get(bits)))

        # non-layer params (embed/pos/final norm/head) live in HBM; init with
        # an L=1 config and drop the layers subtree
        def nl_init(key):
            full = init_params(key, cfg1)
            return {k: jax.tree.map(lambda a: a.astype(cfg.dtype), v)
                    for k, v in full.items() if k != "layers"}

        self.nl_params = jax.jit(nl_init,
                                 out_shardings=self._repl_dev_sh)(keys[L])
        self.nl_opt = jax.tree.map(
            lambda p: {"master": p.astype(jnp.float32),
                       "m": jnp.zeros(p.shape, jnp.float32),
                       "v": jnp.zeros(p.shape, jnp.float32)},
            self.nl_params)
        if self._pinned:
            # embed/head fp32 state (12 bytes/param — GBs at 7B vocab+width)
            # lives on the host tier too
            self.nl_opt = jax.device_put(self.nl_opt, self._repl_host_sh)
        elif self.mesh.size > 1:
            self.nl_opt = jax.device_put(self.nl_opt, self._repl_dev_sh)

        from deepspeed_tpu.ops.adam import adam_tree_update

        def nl_update_tree(opt, grads, lr_t, step, coef):
            """Shared embed/head update over the {master,m,v}-leaf tree."""
            return adam_tree_update(
                opt, grads, lr_t, step, coef, b1=self.b1, b2=self.b2,
                eps=self.eps, wd=self.wd, awm=self.awm, bc=self.bc,
                out_dtype=self.cfg.dtype)

        def nl_adam(opt, grads, params, lr_t, step, coef):
            return nl_update_tree(opt, grads, lr_t, step, coef)

        self._nl_adam = jax.jit(nl_adam, donate_argnums=(0,))

        if self._host_adam == "xla_host":
            # embed/head update on the TPU host too: its fp32 state
            # (12 bytes/param — GBs at 7B vocab+width) stops round-tripping
            # host<->HBM; per step only compute-dtype grads go down and
            # compute-dtype params come back up.
            from jax.experimental.compute_on import compute_on

            def nl_adam_host(opt, grads, lr_t, step, coef):
                @compute_on("device_host")
                @jax.jit
                def upd_all(opt, grads, lr_t, step, coef):
                    return nl_update_tree(opt, grads, lr_t, step, coef)
                return upd_all(opt, grads, lr_t, step, coef)

            host_of = lambda t: jax.tree.map(  # noqa: E731
                lambda _: self._repl_host_sh, t)
            grads_shape = jax.tree.map(
                lambda o: o["master"], self.nl_opt,
                is_leaf=lambda x: isinstance(x, dict) and "master" in x)
            self._nl_adam_host = jax.jit(
                nl_adam_host,
                in_shardings=(host_of(self.nl_opt), host_of(grads_shape),
                              self._repl_host_sh, self._repl_host_sh,
                              self._repl_host_sh),
                out_shardings=(host_of(self.nl_opt), host_of(grads_shape)),
                donate_argnums=(0,))
            self._nl_grads_host_sh = host_of(grads_shape)

    # ------------------------------------------------------------------
    # IO helpers (prefetched)
    # ------------------------------------------------------------------
    def _get_param(self, i: int):
        got = self._param_cache.get(i)
        if got is None:
            got = self.store.read_param(i)
            if got is None:
                raise RuntimeError(f"missing param chunk {i}")
            if len(self._param_cache) < self._cache_layers:
                self._param_cache[i] = got
        return got

    def _param_dev(self, i: int):
        """Device handle for layer i's param bits. Live-cached layers skip
        IO entirely. Pinned backend: eager pinned_host->HBM DMA (async
        dispatch — issuing it a layer ahead IS the prefetch). File
        backends: host numpy (the jit call uploads; multi-device meshes
        shard the upload so each chip receives only its fsdp slice)."""
        got = self._hbm_cache.get(i)
        if got is not None:
            return got
        h = self._get_param(i)
        if self._pinned or self.mesh.size > 1:
            h = jax.device_put(h, self._bits_dev_sh)
        if self._hbm_cache_layers and \
                len(self._hbm_cache) < self._hbm_cache_layers:
            if not (self._pinned or self.mesh.size > 1):
                h = jnp.asarray(h)   # materialize on device for the cache
            self._hbm_cache[i] = h
        return h

    def _refresh_live_cache(self, i: int, bits, *, from_host: bool = False):
        """After an update, keep layer i's NEW bits live in device memory
        (within budget) so the next fwd/bwd skips the fetch."""
        if not self._hbm_cache_layers:
            return
        if i in self._hbm_cache or \
                len(self._hbm_cache) < self._hbm_cache_layers:
            self._hbm_cache[i] = (jax.device_put(bits, self._bits_dev_sh)
                                  if from_host else bits)

    def _fetch_param_async(self, i: int):
        got = self._hbm_cache.get(i)
        if got is not None:
            return got
        if self._pinned:
            return self._param_dev(i)  # async dispatch, returns a handle
        if not self.pipeline:
            return None   # drained executor: resolve-at-use, synchronously
        if i in self._param_cache:
            return None
        return self._rpool.submit(self._get_param, i)

    def _stream_params(self, order):
        """Yield ``(i, resolved_bits)`` over layer indices ``order``,
        keeping TWO fetches in flight ahead of the consumer (double-
        buffered streaming): while layer i computes, layer order[+1]'s
        read is resolving and order[+2]'s is queued on the read pool.
        pipeline=False degrades to synchronous resolve-at-use.

        Pinned backend stays at depth 1: there a "fetch" IS the
        pinned->HBM device_put dispatch, so each prefetched layer is
        DEVICE-resident bits — depth 2 would hold a third layer's chunk
        in HBM on rungs sized for two (the 7B capacity rung budgets one
        working layer + one prefetch), for no IO win over the already-
        async dispatch."""
        order = list(order)
        depth = (1 if self._pinned else 2) if self.pipeline else 0
        futs = {}
        for k in order[:depth]:
            futs[k] = self._fetch_param_async(k)
        for pos, i in enumerate(order):
            fut = futs.pop(i, None)
            if depth and pos + depth < len(order):
                nxt = order[pos + depth]
                futs[nxt] = self._fetch_param_async(nxt)
            yield i, self._resolve_param(fut, i)

    def _resolve_param(self, fut, i: int):
        if fut is not None and not hasattr(fut, "result"):
            return fut   # already a device handle (live cache / pinned)
        if self._pinned:
            return fut if fut is not None else self._param_dev(i)
        h = fut.result() if fut is not None else self._get_param(i)
        if self.mesh.size > 1:
            # sharded upload: each chip receives only its fsdp slice (the
            # in-graph all-gather redistributes over ICI, not host links)
            return jax.device_put(h, self._bits_dev_sh)
        return h

    def _to_host(self, x_dev, host_sh=None):
        """Stage a device array on the TPU host (pinned) or here (numpy)."""
        if self._pinned:
            return jax.device_put(x_dev, host_sh or self._bits_host_sh)
        return np.asarray(jax.device_get(x_dev))

    def _to_dev(self, h, dev_sh=None):
        if self._pinned or self.mesh.size > 1:
            return jax.device_put(h, dev_sh or self._bits_dev_sh)
        return jnp.asarray(h)

    def _drain_write(self):
        """Drain ALL in-flight write-behind. Called only at step
        boundaries (and on overflow/checkpoint/close) — never inside the
        sweeps, where it would serialize the pipeline."""
        pend, self._pending_writes = self._pending_writes, []
        for f in pend:
            f.result()

    def _bound_writes(self, limit: int = 2):
        """Write-behind depth: two writes in flight (double buffer);
        the oldest completes before a third is queued."""
        while len(self._pending_writes) >= limit:
            self._pending_writes.pop(0).result()

    def _write_layer_async(self, i: int, opt_buf_dev, bits_dev):
        if self._pinned:
            # device->pinned_host DMAs dispatch asynchronously; the store
            # keeps the handles
            self.store.write_opt(i, opt_buf_dev)
            self.store.write_param(i, bits_dev)
            return

        def work(opt_dev, bits_dev):
            # the device_get runs ON the writer thread: the main thread
            # keeps dispatching chunk i+1's update while chunk i's result
            # drains off the device and onto storage
            opt_host = np.asarray(jax.device_get(opt_dev))
            bits_host = np.asarray(jax.device_get(bits_dev))
            self.store.write_opt(i, opt_host)
            self.store.write_param(i, bits_host)
            if i in self._param_cache or len(self._param_cache) < self._cache_layers:
                self._param_cache[i] = bits_host

        if not self.pipeline:
            # drained twin: write synchronously, nothing in flight past
            # this layer
            work(opt_buf_dev, bits_dev)
            return
        self._bound_writes()
        self._pending_writes.append(
            self._wpool.submit(work, opt_buf_dev, bits_dev))

    # ------------------------------------------------------------------
    def _batch_arrays(self, batch):
        ids = jnp.asarray(batch["input_ids"])
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, ids.dtype)],
                axis=1)
        else:
            labels = jnp.asarray(labels)
        mask = batch.get("attention_mask")
        if mask is not None:
            mask = jnp.asarray(mask)
        if self.mesh.size > 1:
            mb = ids.shape[0] // self.gas if self.gas > 1 else ids.shape[0]
            if mb % self.dp:
                raise ValueError(
                    f"microbatch {mb} not divisible by data*fsdp={self.dp}")
            ids = jax.device_put(ids, self._x_sh)
            labels = jax.device_put(labels, self._x_sh)
            if mask is not None:
                mask = jax.device_put(mask, self._x_sh)
        return ids, labels, mask

    def train_batch(self, batch) -> Dict[str, Any]:
        """One optimizer step: forward/backward sweeps over the layer files,
        host-staged grads, global-norm clip, fused-Adam update sweep. The
        mesh context makes the jits' sharding constraints resolvable
        (no-op on the 1-device mesh)."""
        with self.mesh:
            return self._train_batch(batch)

    def measure_decomposition(self, batch, reps: int = 2) -> Dict[str, float]:
        """Measured transfer-vs-compute decomposition of the streamed step
        (VERDICT Weak #2: the offload ratio was prose, not attributable).

        Direct measurements, no modeling:
          - ``offload_chunk_dma_ms``: wall time to stage ONE layer's param
            chunk host->device (the store's own staging path) with a fence;
          - ``offload_layer_ms``: wall time of one layer's fwd+bwd with the
            bits already device-resident (pure compute) with a fence;
          - ``offload_update_ms`` / ``offload_top_ms`` /
            ``offload_opt_io_ms``: the update sweep's three legs — one
            chunk's Adam compute, the embed/CE-head top (once per step),
            and one opt chunk's storage round-trip.
        Scaled to the step: param DMA crosses twice per layer (fwd + bwd
        fetch — ``offload_dma_ms``), layer fwd+bwd and the chunk Adam run
        once per layer (``offload_compute_ms`` /
        ``offload_update_sweep_ms``), and ``offload_io_ms`` totals the
        step's storage traffic (param fetches + opt round-trips).
        Callers price overlap through
        ``profiling.doctor.diagnose_offload``: exposure =
        max(0, step_ms - ALL measured compute) clamped to the io budget,
        ``offload_overlap_fraction = 1 - exposed/io`` — the storage time
        the step did NOT hide under compute.
        """
        import time
        with self.mesh:
            ids, labels, mask = self._batch_arrays(batch)
            mb = ids.shape[0] // self.gas if self.gas > 1 else ids.shape[0]
            ids, labels = ids[:mb], labels[:mb]
            mask = mask[:mb] if mask is not None else None
            L = self.cfg.num_layers
            step_t = jnp.int32(self.applied_steps)
            qb = jnp.float32(32.0)

            def fence(a):
                return np.asarray(jax.device_get(jnp.ravel(a)[0]))

            x = self._embed_fwd(self.nl_params, ids)
            fence(x)
            bits = self._to_dev(self._get_param(0))
            dy = jnp.ones_like(x)
            # warm the compiles outside the timed region
            y = self._layer_fwd(bits, x, mask, None, step_t, qb)
            _, _, sq = self._layer_bwd(bits, x, dy, mask, None, step_t, qb)
            fence(y)
            fence(sq)
            t0 = time.perf_counter()
            for _ in range(reps):
                y = self._layer_fwd(bits, x, mask, None, step_t, qb)
                _, _, sq = self._layer_bwd(bits, x, dy, mask, None,
                                           step_t, qb)
                fence(sq)
            layer_ms = (time.perf_counter() - t0) / reps * 1000
            # DMA probe: the same staging path the sweeps use
            h = self._get_param(0)
            d = self._to_dev(h)
            fence(d)
            t0 = time.perf_counter()
            for _ in range(reps):
                d = self._to_dev(h)
                fence(d)
            chunk_ms = (time.perf_counter() - t0) / reps * 1000

            # --- update-sweep probes (the pipelined sweep's three legs:
            # what the Adam compute costs, what the embed/head top costs,
            # and what one opt chunk's storage round-trip costs — callers
            # price exposure against compute INCLUDING these, so the
            # overlap fraction attributes the sweep too, not just the
            # fwd/bwd fetches)
            update_ms = top_ms = opt_io_ms = 0.0
            try:
                top_ms = self._measure_top_ms(ids, labels, scale=1.0,
                                              reps=reps)
            except Exception:   # noqa: BLE001 — secondary probe
                pass
            try:
                update_ms = self._measure_update_ms(reps=reps)
            except Exception:   # noqa: BLE001 — secondary probe
                pass
            try:
                if self.store.backend == "nvme":
                    opt0 = self.store.read_opt(0)
                    if opt0 is not None:
                        t0 = time.perf_counter()
                        for _ in range(reps):
                            opt0 = self.store.read_opt(0)
                            # same bytes back: a pure IO probe, no state
                            # change
                            self.store.write_opt(0, opt0)
                        opt_io_ms = ((time.perf_counter() - t0) / reps
                                     * 1000)
            except Exception:   # noqa: BLE001 — secondary probe
                pass
        io_ms = chunk_ms * 2 * L + opt_io_ms * L
        return {
            "offload_chunk_dma_ms": round(chunk_ms, 3),
            "offload_layer_ms": round(layer_ms, 3),
            # per step: every layer's chunk is fetched twice (fwd sweep +
            # bwd sweep); its fwd and bwd each run once — layer_ms times
            # them together
            "offload_dma_ms": round(chunk_ms * 2 * L, 2),
            "offload_compute_ms": round(layer_ms * L, 2),
            # the sweep legs: per-layer Adam compute, embed/head top
            # compute (once per step), per-layer opt-chunk storage IO,
            # and the step's TOTAL io (param fetches + opt round-trips)
            "offload_update_ms": round(update_ms, 3),
            "offload_update_sweep_ms": round(update_ms * L, 2),
            "offload_top_ms": round(top_ms, 2),
            "offload_opt_io_ms": round(opt_io_ms, 3),
            "offload_io_ms": round(io_ms, 2),
            "offload_pipeline": bool(self.pipeline),
        }

    def _measure_top_ms(self, ids, labels, scale: float, reps: int) -> float:
        """Embed fwd + CE-head fwd/bwd + embed bwd wall time (the step's
        non-layer compute)."""
        import time
        scale_t = jnp.float32(scale)
        x = self._embed_fwd(self.nl_params, ids)
        loss, dnl, dx = self._top_fwd_bwd(self.nl_params, x, labels, scale_t)
        dnl_e = self._embed_bwd(self.nl_params, ids, dx)
        np.asarray(jax.device_get(loss))
        jax.tree.leaves(jax.device_get(dnl_e))
        t0 = time.perf_counter()
        for _ in range(reps):
            x = self._embed_fwd(self.nl_params, ids)
            loss, dnl, dx = self._top_fwd_bwd(self.nl_params, x, labels,
                                              scale_t)
            dnl_e = self._embed_bwd(self.nl_params, ids, dx)
            np.asarray(jax.device_get(jnp.ravel(
                jax.tree.leaves(dnl_e)[0])[0]))
        return (time.perf_counter() - t0) / reps * 1000

    def _measure_update_ms(self, reps: int) -> float:
        """One layer chunk's Adam update cost on scratch state — the
        compute leg of the update sweep (no store writes)."""
        import time
        if self._host_adam == "native":
            from deepspeed_tpu.ops.cpu_adam import adam_step_flat
            scratch = np.zeros((_PLANES, self.chunk), np.float32)
            g = np.zeros(self.chunk, np.float32)
            t0 = time.perf_counter()
            for _ in range(reps):
                adam_step_flat(scratch[0], scratch[1], scratch[2], g,
                               step_num=1, lr=self.lr
                               if not callable(self.lr) else self.lr(1),
                               betas=(self.b1, self.b2), eps=self.eps,
                               weight_decay=self.wd, adamw_mode=self.awm,
                               bias_correction=self.bc, grad_scale=1.0)
            return (time.perf_counter() - t0) / reps * 1000
        lr_t, stepc, coef_t = (jnp.float32(1e-3), jnp.float32(1.0),
                               jnp.float32(1.0))
        if self._host_adam == "xla_host":
            lr_h, step_h, coef_h = jax.device_put((lr_t, stepc, coef_t),
                                                  self._repl_host_sh)
            pbits = self.store.read_param(0)
            gbits = self._to_host(self._grad_bits(
                jnp.zeros((self.chunk,), jnp.float32)))
            # warm
            _o, _b, fence = self._adam_chunk_host(
                self._zeros_opt_host(), gbits, pbits, lr_h, step_h,
                coef_h, False)
            np.asarray(jax.device_get(fence))
            t0 = time.perf_counter()
            for _ in range(reps):
                _o, _b, fence = self._adam_chunk_host(
                    self._zeros_opt_host(), gbits, pbits, lr_h, step_h,
                    coef_h, False)
                np.asarray(jax.device_get(fence))
            return (time.perf_counter() - t0) / reps * 1000
        g_dev = jnp.zeros((self.chunk,), jnp.float32)
        pbits = self._param_dev(0)
        _buf, _bits = self._adam_chunk(self._zeros_opt(), g_dev, pbits,
                                       jnp.asarray(False), lr_t, stepc,
                                       coef_t)
        np.asarray(jax.device_get(_bits[0]))
        t0 = time.perf_counter()
        for _ in range(reps):
            _buf, _bits = self._adam_chunk(self._zeros_opt(), g_dev, pbits,
                                           jnp.asarray(False), lr_t, stepc,
                                           coef_t)
            np.asarray(jax.device_get(_bits[0]))
        return (time.perf_counter() - t0) / reps * 1000

    def _qbits(self, batch, i: int):
        """Layer i's traced MoQ bit-width (engine side-channel), or a dummy
        scalar when MoQ is off (the jit operand is dead code then)."""
        if self.moq and isinstance(batch, dict) and "_moq_bits" in batch:
            return jnp.float32(np.asarray(batch["_moq_bits"])[i])
        return jnp.float32(32.0)

    def _train_batch(self, batch) -> Dict[str, Any]:
        L = self.cfg.num_layers
        ids_all, labels_all, mask_all = self._batch_arrays(batch)
        B = ids_all.shape[0]
        gas = self.gas
        mb = B // gas if gas > 1 else B

        # host fp32 grad staging, accumulated across microbatches
        grad_stage = [None] * L
        nl_grads = None
        loss_sum = 0.0
        sq_layer = [0.0] * L

        scale = self._scale if self.fp16 else 1.0
        scale_t = jnp.float32(scale)
        step_t = jnp.int32(self.applied_steps)

        # ---- update/backward overlap (xla_host Adam only) ----
        # With no clip, no fp16 overflow gate, and gas=1, the Adam update
        # for layer i depends only on layer i's grads (coef = 1 is known
        # up front) — so it can dispatch the moment layer i's grads are
        # staged, and the TPU-host cores run the Adam sweep CONCURRENTLY
        # with the device's backward of the remaining layers. (The generic
        # path must wait for the global grad norm.)
        overlap = (self._host_adam == "xla_host" and gas == 1
                   and not self.fp16
                   and not (self.clip and self.clip > 0))
        overlap_fence = None
        pending_refresh = []
        if overlap:
            step_next = self.applied_steps + 1
            lr_val = (self.lr if not callable(self.lr)
                      else self.lr(step_next))
            ov_lr, ov_step, ov_coef = jax.device_put(
                (jnp.float32(lr_val), jnp.float32(step_next),
                 jnp.float32(1.0)), self._repl_host_sh)

        for g in range(gas):
            sl = slice(g * mb, (g + 1) * mb) if gas > 1 else slice(None)
            ids, labels = ids_all[sl], labels_all[sl]
            mask = mask_all[sl] if mask_all is not None else None
            positions = None

            # ---- forward sweep (double-buffered: two fetches in flight
            # ahead of compute; _stream_params resolves at use) ----
            x = self._embed_fwd(self.nl_params, ids)
            acts = [x]
            for i, bits in self._stream_params(range(L)):
                x = self._layer_fwd(bits, x, mask, positions, step_t,
                                    self._qbits(batch, i))
                acts.append(x)
                if not self.pipeline:
                    # fully-drained executor: fence the layer before the
                    # next synchronous fetch — fetch -> compute -> drain,
                    # strictly in sequence (the offload-serial-pipeline
                    # corpus shape; async dispatch would otherwise still
                    # hide the next fetch under this layer's compute)
                    np.asarray(jax.device_get(jnp.ravel(x)[0]))

            loss, dnl_top, dx = self._top_fwd_bwd(self.nl_params, acts[L],
                                                  labels, scale_t)
            loss_sum += float(np.asarray(jax.device_get(loss))) / scale

            # ---- backward sweep (reverse, double-buffered: two fetches
            # in flight behind the walk) ----
            last_mb = g == gas - 1
            for i, bits in self._stream_params(range(L - 1, -1, -1)):
                dp, dx, sq = self._layer_bwd(bits, acts[i], dx, mask,
                                             positions, step_t,
                                             self._qbits(batch, i))
                acts[i + 1] = None  # free the activation as we pass it
                if self._pinned:
                    if grad_stage[i] is not None:  # accumulate on device
                        dp = self._scalar_add(self._to_dev(grad_stage[i]), dp)
                        if last_mb:
                            sq = self._sq(dp)
                    if overlap:
                        # stage bf16 grad bits and dispatch the host Adam
                        # for this layer right now — it runs on the TPU
                        # host while the device keeps doing backward
                        gbits = self._to_host(self._grad_bits(dp))
                        opt_h = self.store.read_opt(i)
                        have = opt_h is not None
                        if not have:
                            opt_h = self._zeros_opt_host()
                        new_opt, new_bits, overlap_fence = \
                            self._adam_chunk_host(
                                opt_h, gbits, self.store.read_param(i),
                                ov_lr, ov_step, ov_coef, have)
                        self.store.write_opt(i, new_opt)
                        self.store.write_param(i, new_bits)
                        # cache refresh is DEFERRED to after the backward:
                        # an eager pinned->HBM device_put here would make
                        # the device stream wait on this layer's host Adam
                        # before running the next backward layer
                        pending_refresh.append((i, new_bits))
                    elif last_mb and self._host_adam == "xla_host":
                        # final stage in bf16 bits — the host-Adam wire
                        # dtype (halves the grad DMA; reference ships f16
                        # grads to its CPU-Adam the same way)
                        grad_stage[i] = self._to_host(self._grad_bits(dp))
                    else:
                        grad_stage[i] = self._to_host(dp)
                    sq_layer[i] = sq
                else:
                    dp_host = np.asarray(jax.device_get(dp))
                    if grad_stage[i] is None:
                        # device_get buffers are read-only; copy only when
                        # we must accumulate into them
                        grad_stage[i] = dp_host if gas == 1 else dp_host.copy()
                    else:
                        grad_stage[i] += dp_host
                    sq_layer[i] = sq  # device scalar; summed after the loop

            dnl_emb = self._embed_bwd(self.nl_params, ids, dx)
            dnl = self._tree_add(dnl_top, dnl_emb)
            nl_grads = dnl if nl_grads is None else self._tree_add(nl_grads,
                                                                   dnl)

        # ---- global grad norm + overflow + clip coefficient ----
        inv = 1.0 / gas
        sq_total = 0.0
        for i in range(L):
            # staged grads are microbatch SUMS; norm uses the mean
            if gas == 1 or self._pinned:
                s = float(np.asarray(jax.device_get(sq_layer[i]))) * inv * inv
            else:
                s = float(np.sum((grad_stage[i] * inv) ** 2))
            sq_total += s
        nl_sq = float(np.asarray(jax.device_get(
            self._nl_sq(nl_grads, jnp.float32(inv)))))
        if self.fp16 and not np.isfinite(sq_total + nl_sq):
            # overflow: nothing is written (chunks untouched), the loss
            # scale shrinks — reference: loss_scaler.py:84 + step:1635
            self._on_overflow()
            self._drain_write()
            return {"loss": jnp.float32(loss_sum / gas),
                    "grad_norm": jnp.float32(float("nan")),
                    "overflow": jnp.asarray(True),
                    "loss_scale": jnp.float32(self._scale)}
        gnorm = math.sqrt(sq_total + nl_sq) / scale
        coef = inv / scale
        if self.clip and self.clip > 0 and gnorm > self.clip:
            coef *= self.clip / (gnorm + 1e-6)
        if self.fp16:
            self._on_good_step()

        # ---- update sweep ----
        self.applied_steps += 1
        lr_t = jnp.float32(self.lr if not callable(self.lr)
                           else self.lr(self.applied_steps))
        stepc = jnp.float32(self.applied_steps)
        coef_t = jnp.float32(coef)

        # non-layer (embed/head) update first: frees its fp32 grads before
        # the layer sweep's chunk buffers arrive
        if self._host_adam == "xla_host":
            # embed/head Adam on the TPU host: stage 2-byte grads down,
            # bring compute-dtype params up — the fp32 state stays
            # pinned-resident. Wire is bf16 even under fp16: scaled fp32
            # embed grads can exceed f16's 65504 max, which would silently
            # become inf AFTER the overflow check already passed
            wire = (jnp.bfloat16 if self.cfg.dtype == jnp.float16
                    else self.cfg.dtype)
            nl_g_host = jax.device_put(
                jax.tree.map(lambda g: g.astype(wire), nl_grads),
                self._nl_grads_host_sh)
            lr_h, step_h, coef_h = jax.device_put(
                (lr_t, stepc, coef_t), self._repl_host_sh)
            self.nl_opt, nl_params_host = self._nl_adam_host(
                self.nl_opt, nl_g_host, lr_h, step_h, coef_h)
            self.nl_params = jax.device_put(nl_params_host,
                                            self._repl_dev_sh)
        else:
            nl_opt_dev = (jax.device_put(self.nl_opt, self._repl_dev_sh)
                          if self._pinned else self.nl_opt)
            new_nl_opt, self.nl_params = self._nl_adam(
                nl_opt_dev, nl_grads, self.nl_params, lr_t, stepc, coef_t)
            self.nl_opt = (jax.device_put(new_nl_opt, self._repl_host_sh)
                           if self._pinned else new_nl_opt)
        del nl_grads

        if overlap:
            # layer updates were dispatched during backward; one tail fence
            # orders them before the step returns. Cache refreshes go out
            # now — each pinned->HBM transfer depends only on its own
            # layer's host Adam, so they pipeline with the sweep's tail.
            for i_r, bits_r in pending_refresh:
                self._refresh_live_cache(i_r, bits_r, from_host=True)
            pending_refresh.clear()
            if overlap_fence is not None:
                np.asarray(jax.device_get(overlap_fence))
        elif self._host_adam == "xla_host":
            # opt chunks never leave pinned_host: the Adam sweep runs on the
            # TPU host's cores (compute_on). No per-layer fence needed — the
            # chunks stay host-side, so nothing piles up in HBM; one tail
            # fence orders the sweep before the step returns.
            fence = None
            lr_h, step_h, coef_h = jax.device_put(
                (lr_t, stepc, coef_t), self._repl_host_sh)
            for i in range(L):
                opt_h = self.store.read_opt(i)
                have = opt_h is not None
                if not have:
                    opt_h = self._zeros_opt_host()
                new_opt, new_bits, fence = self._adam_chunk_host(
                    opt_h, grad_stage[i], self.store.read_param(i),
                    lr_h, step_h, coef_h, have)
                grad_stage[i] = None
                self.store.write_opt(i, new_opt)
                self.store.write_param(i, new_bits)
                self._refresh_live_cache(i, new_bits, from_host=True)
            if fence is not None:
                np.asarray(jax.device_get(fence))
        elif self._host_adam == "native":
            self._native_update_sweep(grad_stage, float(lr_t), coef)
        else:
            # three-way pipelined sweep (reference schedule,
            # swap_tensor.py:16):  read(i+1)  ||  adam(i) on device  ||
            # write(i-1).  Opt reads prefetch on the read pool, the write-
            # behind (device_get runs ON the writer thread) drains on the
            # write pool two layers deep, and _drain_write happens only at
            # the step boundary below. The drained twin (pipeline=False)
            # resolves reads at use and syncs every write. Reads come back
            # as fresh host arrays (no staging reuse here: the jit upload
            # may be zero-copy on CPU jaxlibs, so a recycled buffer could
            # alias a live device array — the native host-Adam sweep is
            # where the rotating staging buffers live).
            pipe = self.pipeline and not self._pinned
            opt_fut = self._rpool.submit(self.store.read_opt, 0) \
                if pipe else None
            for i in range(L):
                opt_host = (opt_fut.result() if pipe
                            else self.store.read_opt(i))
                if pipe:
                    opt_fut = (self._rpool.submit(self.store.read_opt, i + 1)
                               if i + 1 < L else None)
                have = opt_host is not None
                opt_dev = (self._to_dev(opt_host, self._opt_dev_sh) if have
                           else self._zeros_opt())
                new_buf, new_bits = self._adam_chunk(
                    opt_dev, self._to_dev(grad_stage[i]), self._param_dev(i),
                    jnp.asarray(have), lr_t, stepc, coef_t)
                grad_stage[i] = None
                self._write_layer_async(i, new_buf, new_bits)
                self._refresh_live_cache(i, new_bits)
                if self._pinned:
                    # bound in-flight chunk buffers to one layer: at 7B a
                    # layer's (3, C) fp32 opt buffer is 2.4 GB, and letting
                    # the async dispatch run ahead piles up donated+new
                    # buffers past HBM. (block_until_ready is a no-op through
                    # the relay transport; a scalar fetch is the reliable
                    # fence.)
                    np.asarray(jax.device_get(new_buf[0, 0]))
                del opt_dev, new_buf, new_bits
        self._drain_write()

        out = {"loss": jnp.float32(loss_sum / gas),
               "grad_norm": jnp.float32(gnorm),
               "overflow": jnp.zeros((), jnp.bool_)}
        if self.fp16:
            out["loss_scale"] = jnp.float32(scale)
        return out

    def _opt_read_staged(self, i: int):
        """Read opt chunk i into one of the three rotating host staging
        buffers (lazy-init from the bf16 params when the chunk is missing).
        Waits for any write-behind still draining the target buffer, so
        read(i+1), update(i) and write(i-1) can all be in flight at once
        without aliasing. Only meaningful for the native host-Adam sweep,
        whose consumption is pure numpy (in-place update + same-buffer
        write)."""
        import ml_dtypes
        buf = self._opt_stage.acquire(i)
        got = self.store.read_opt(i, out=buf)
        if got is None:   # lazy init: master from the bf16 params
            np.copyto(buf[0], self._get_param(i).view(ml_dtypes.bfloat16))
            buf[1:] = 0.0
            return buf
        # host backend returns the stored array itself (out is ignored
        # there) — same in-place-update-then-copy-back contract as before
        return np.ascontiguousarray(got)

    def _native_update_sweep(self, grad_stage, lr: float, coef: float):
        """Fused C++ AdamW (csrc/adam/dstpu_cpu_adam.cpp) over the store's
        chunks — this process IS the TPU host, so the fp32 state never
        touches the device; updated bf16 param bits are derived host-side.
        Pipelined as the reference's three-stage optimizer swapper
        (pipelined_optimizer_swapper.py:50): chunk i+1's AIO read fills one
        staging buffer while the host cores run Adam on chunk i in a second
        and the write ring drains chunk i-1 from the third.
        Reference: stage_1_and_2.py's cpu_offload step over DeepSpeedCPUAdam."""
        import ml_dtypes
        from deepspeed_tpu.ops.cpu_adam import adam_step_flat
        L = self.cfg.num_layers
        step = self.applied_steps
        pipe = self.pipeline
        if self._opt_stage is None:
            self._opt_stage = StagingRing(3, (_PLANES, self.chunk),
                                          np.float32)
        opt_fut = self._rpool.submit(self._opt_read_staged, 0) \
            if pipe else None
        for i in range(L):
            opt = opt_fut.result() if pipe else self._opt_read_staged(i)
            if pipe:
                opt_fut = (self._rpool.submit(self._opt_read_staged, i + 1)
                           if i + 1 < L else None)
            adam_step_flat(opt[0], opt[1], opt[2], grad_stage[i],
                           step_num=step, lr=lr, betas=(self.b1, self.b2),
                           eps=self.eps, weight_decay=self.wd,
                           adamw_mode=self.awm, bias_correction=self.bc,
                           grad_scale=coef)
            grad_stage[i] = None
            bits = np.ascontiguousarray(
                opt[0].astype(ml_dtypes.bfloat16).view(np.uint16))

            def work(i=i, opt=opt, bits=bits):
                self.store.write_opt(i, opt)
                self.store.write_param(i, bits)
                if i in self._param_cache or \
                        len(self._param_cache) < self._cache_layers:
                    self._param_cache[i] = bits

            if pipe:
                self._bound_writes()
                fut = self._wpool.submit(work)
                if opt is self._opt_stage.slot(i):
                    self._opt_stage.mark_busy(i, fut)
                self._pending_writes.append(fut)
            else:
                work()   # drained twin: write + implicit drain per layer
            self._refresh_live_cache(i, bits, from_host=True)

    def _on_overflow(self):
        if not self._dynamic_scale:
            return  # static scale: overflow skips the step, scale holds
        self._hyst_left -= 1
        if self._hyst_left <= 0:
            self._scale = max(self._min_scale, self._scale / 2.0)
            self._hyst_left = self._hysteresis
        self._good_steps = 0

    def _on_good_step(self):
        if not self._dynamic_scale:
            return
        self._good_steps += 1
        if self._good_steps >= self._scale_window:
            self._scale *= 2.0
            self._good_steps = 0
            self._hyst_left = self._hysteresis

    def eval_batch(self, batch):
        L = self.cfg.num_layers
        with self.mesh:
            ids, labels, mask = self._batch_arrays(batch)
            x = self._embed_fwd(self.nl_params, ids)
            for i, bits in self._stream_params(range(L)):
                x = self._layer_fwd(bits, x, mask, None,
                                    jnp.int32(self.applied_steps),
                                    self._qbits(batch, i))
            return self._top_loss(self.nl_params, x, labels)

    # ------------------------------------------------------------------
    def save_checkpoint(self, path: str) -> Dict[str, Any]:
        """Copy chunk files + return the small HBM-resident state for the
        engine's regular checkpoint machinery. A shapes manifest makes the
        chunks self-describing (utils/zero_to_fp32.py reconstructs the fp32
        tree offline with no engine)."""
        import json as _json
        self.store.save_to(os.path.join(path, "infinity_chunks"))
        leaf_names = ["/".join(str(getattr(k, "key", k)) for k in p)
                      for p, _ in jax.tree_util.tree_flatten_with_path(
                          jax.tree.unflatten(self._treedef,
                                             list(range(len(self._sizes)))))[0]]
        with open(os.path.join(path, "infinity_shapes.json"), "w") as f:
            _json.dump({"chunk": self.chunk,
                        "num_layers": self.cfg.num_layers,
                        "leaf_names": leaf_names,
                        "leaf_shapes": [list(s) for s in self._shapes]}, f)
        out = {"nl_params": jax.device_get(self.nl_params),
               "nl_opt": jax.device_get(self.nl_opt),
               "applied_steps": self.applied_steps}
        if self.fp16:
            out["loss_scale"] = [self._scale, self._good_steps,
                                 self._hyst_left]
        return out

    def load_checkpoint(self, path: str, small_state: Dict[str, Any]):
        import json as _json
        saved_chunk = None
        manifest = os.path.join(path, "infinity_shapes.json")
        if os.path.exists(manifest):
            with open(manifest) as f:
                meta = _json.load(f)
            saved_chunk = meta.get("chunk")
            if meta.get("num_layers") != self.cfg.num_layers:
                raise ValueError(
                    f"checkpoint has {meta.get('num_layers')} layers, model "
                    f"has {self.cfg.num_layers}")
            # re-chunking only ever touches the zero-pad region: both the
            # saved and the current chunk are >= the real layer numel
        self.store.load_from(os.path.join(path, "infinity_chunks"),
                             saved_chunk=saved_chunk)
        self._param_cache.clear()
        self._hbm_cache.clear()
        self.nl_params = jax.tree.map(jnp.asarray, small_state["nl_params"])
        self.nl_opt = jax.tree.map(jnp.asarray, small_state["nl_opt"])
        if self._pinned:
            self.nl_opt = jax.device_put(self.nl_opt, self._repl_host_sh)
        elif self.mesh.size > 1:
            self.nl_params = jax.device_put(self.nl_params, self._repl_dev_sh)
            self.nl_opt = jax.device_put(self.nl_opt, self._repl_dev_sh)
        self.applied_steps = int(small_state["applied_steps"])
        if self.fp16 and "loss_scale" in small_state:
            s, g, h = [float(x) for x in np.asarray(
                small_state["loss_scale"]).reshape(-1)]
            self._scale, self._good_steps, self._hyst_left = s, int(g), int(h)

    def close(self):
        self._drain_write()
        self._rpool.shutdown(wait=True)
        self._wpool.shutdown(wait=True)
        self.store.close()
