"""Data loading.

Reference: ``deepspeed/runtime/dataloader.py`` (DeepSpeedDataLoader wrapping a
DistributedSampler, RepeatingLoader). Under SPMD one process feeds the global
batch; sharding happens at device_put, so the "distributed sampler" is just
batch slicing per host in the multi-host case (each host yields its slice of
the global batch; jax.make_array_from_process_local_data assembles it).
"""

import collections
import math
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np


class DataLoader:
    """Minimal batching loader over an indexable dataset of dict rows (or a
    callable index -> row)."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = True, collate_fn=None,
                 sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        if sampler is not None and shuffle:
            raise ValueError("pass shuffle to the sampler, not the loader, "
                             "when a sampler is given")
        self.sampler = sampler  # e.g. data_pipeline.DistributedSampler
        self.epoch = 0
        self._pos = 0          # batches yielded this epoch (ckpt position)
        self._resume_pos = 0   # batches to skip on the next __iter__

    def __len__(self):
        total = (len(self.sampler) if self.sampler is not None
                 else len(self.dataset))
        n = total // self.batch_size
        if not self.drop_last and total % self.batch_size:
            n += 1
        return n

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self._pos = 0

    # -- checkpointable position (robustness: elastic resume must neither
    # replay nor skip data). The order within an epoch is a pure function
    # of (seed, epoch), so (epoch, pos, seed) fully names the position.
    def state_dict(self) -> dict:
        return {"epoch": self.epoch, "pos": self._pos, "seed": self.seed}

    def load_state_dict(self, sd: dict) -> None:
        self.seed = int(sd.get("seed", self.seed))
        self.set_epoch(int(sd.get("epoch", 0)))
        # fast-forward happens lazily at the next __iter__: the shuffle
        # order is regenerated from (seed, epoch) and `pos` batches are
        # skipped, so the next yielded batch is exactly the first one the
        # saved run had not consumed
        self._resume_pos = int(sd.get("pos", 0))
        self._pos = self._resume_pos

    def __iter__(self) -> Iterator:
        if self.sampler is not None:
            if hasattr(self.sampler, "set_epoch"):
                self.sampler.set_epoch(self.epoch)
            order = np.fromiter(iter(self.sampler), dtype=np.int64)
            n = len(order)
        else:
            n = len(self.dataset)
            order = np.arange(n)
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self.epoch)
                rng.shuffle(order)
        skip, self._resume_pos = self._resume_pos, 0
        self._pos = skip
        starts = range(0, n - (self.batch_size - 1 if self.drop_last else 0),
                       self.batch_size)
        for bi, start in enumerate(starts):
            if bi < skip:
                continue
            idx = order[start:start + self.batch_size]
            rows = [self.dataset[int(i)] for i in idx]
            self._pos = bi + 1
            yield self.collate_fn(rows)


class PrefetchLoader:
    """Double-buffered device prefetch for the async step pipeline.

    Wraps any host-batch iterable and starts the sharding-aware
    ``device_put`` of batch N+1 while the consumer runs step N: JAX dispatch
    is asynchronous, so ``put_fn`` returns as soon as the H2D transfer is
    *queued* and the copy overlaps the in-flight step instead of sitting on
    the dispatch critical path (the reference hides the same latency behind
    a side CUDA stream).

    ``put_fn`` is typically ``engine._device_batch`` — idempotent: a leaf
    already placed with the target sharding passes through untouched, so the
    engine's curriculum/LTD/PLD batch rewrites compose (a rewritten leaf is
    simply re-placed at consume time).

    ``depth=2`` is classic double buffering; higher depths only help when
    batch production (collate) is burstier than one step. Batch ORDER is the
    wrapped loader's order — prefetch reorders nothing, including across
    epoch boundaries (``set_epoch``/``epoch`` proxy through).

    ``tracer`` (a telemetry ``StepTracer``) records each device_put top-up
    as a ``prefetch`` span in the step trace timeline.
    """

    def __init__(self, loader, put_fn: Callable[[Any], Any], depth: int = 2,
                 tracer=None):
        if put_fn is None:
            raise ValueError("PrefetchLoader needs a device placement fn "
                             "(engine._device_batch)")
        self.loader = loader
        self.put_fn = put_fn
        self.depth = max(1, int(depth))
        self.tracer = tracer

    def _put(self, batch):
        if self.tracer is not None:
            with self.tracer.span("prefetch", cat="data"):
                return self.put_fn(batch)
        return self.put_fn(batch)

    def __len__(self):
        return len(self.loader)

    @property
    def epoch(self):
        return getattr(self.loader, "epoch", 0)

    def set_epoch(self, epoch: int):
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __iter__(self) -> Iterator:
        it = iter(self.loader)
        buf = collections.deque()
        try:
            while len(buf) < self.depth:
                buf.append(self._put(next(it)))
        except StopIteration:
            pass
        while buf:
            out = buf.popleft()
            # top up BEFORE yielding: the put of batch N+depth is queued
            # while the consumer still holds (and then steps on) batch N
            try:
                buf.append(self._put(next(it)))
            except StopIteration:
                pass
            yield out


class RepeatingLoader:
    """Infinite cycling wrapper (reference: dataloader.py RepeatingLoader)."""

    def __init__(self, loader):
        self.loader = loader
        self._it = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self._it = iter(self.loader)
            return next(self._it)

    # position checkpointing proxies (engine.attach_dataloader works with
    # either the bare DataLoader or this wrapper)
    def state_dict(self) -> dict:
        return self.loader.state_dict()

    def load_state_dict(self, sd: dict) -> None:
        self.loader.load_state_dict(sd)
        # drop the live iterator: it was positioned for the OLD state, and
        # DataLoader's lazy fast-forward applies at the next iter()
        self._it = iter(self.loader)


def _default_collate(rows):
    if isinstance(rows[0], dict):
        return {k: np.stack([r[k] for r in rows]) for k in rows[0]}
    if isinstance(rows[0], (tuple, list)):
        return tuple(np.stack([r[i] for r in rows]) for i in range(len(rows[0])))
    return np.stack(rows)


def random_token_batches(batch_size: int, seq_len: int, vocab_size: int,
                         num_batches: int, seed: int = 0):
    """Synthetic LM data (reference: tests/unit/simple_model.py
    random_dataloader equivalent)."""
    rng = np.random.default_rng(seed)
    for _ in range(num_batches):
        ids = rng.integers(0, vocab_size, size=(batch_size, seq_len), dtype=np.int32)
        yield {"input_ids": ids}
