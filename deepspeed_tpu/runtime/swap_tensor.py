"""ZeRO-Infinity: NVMe-resident optimizer state with pipelined swapping.

Reference: ``runtime/swap_tensor/partitioned_optimizer_swapper.py:27`` and
``pipelined_optimizer_swapper.py:50`` (fp32 Adam state lives on NVMe; the
step streams it through device memory with overlapped AIO reads/writes),
plus ``partitioned_param_swapper.py:35`` (param tensors on NVMe).

TPU-native re-design: instead of the reference's per-parameter-group swap
buffers + hooked CPU-Adam, the ENTIRE fp32 state (master weights, exp_avg,
exp_avg_sq) is laid out as fixed-size flat chunks. Adam is elementwise, so
chunk boundaries need not align with parameter boundaries — one jitted
flat-Adam kernel (a single compilation, static chunk shape) serves every
chunk, and chunks are sharded over the whole device mesh so the update rides
all MXU/VPU lanes. Per optimizer step the pipeline is:

    read chunk i+1 (AIO, io_uring)  ||  update chunk i (TPU)  ||  write chunk i-1

HBM residency is O(chunk) instead of O(params): 12 bytes/param of fp32 state
move off-chip, which is what makes "max trainable params per chip"
(BASELINE.md metric #2) scale with NVMe capacity instead of HBM.
"""

import functools
import math
import os
import shutil
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.retry import retry_io
from deepspeed_tpu.utils.logging import logger

# master / exp_avg / exp_avg_sq planes in each chunk buffer
_PLANES = 3


def _flat_spec(mesh) -> P:
    """1-D spec sharding a flat chunk across every device in the mesh."""
    return P(tuple(mesh.axis_names))


class NVMeOptimizerSwapper:
    """fp32 Adam/AdamW state on NVMe, streamed through HBM per step.

    The swapper owns: the chunk files, the jitted flatten/update/unflatten
    programs, and the read/write thread pool. The engine owns: grads, the
    bf16 params, loss scale, and the step counter.
    """

    def __init__(self, param_template, *, mesh, nvme_path: str = None,
                 lr=1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w_mode: bool = True,
                 bias_correction: bool = True,
                 chunk_elems: int = 1 << 24, aio_handle=None,
                 param_shardings=None, grad_shardings=None,
                 compute_dtype=jnp.bfloat16, pipeline: bool = True,
                 host_inputs: bool = False, storage: str = "nvme",
                 aio_config=None):
        """storage: "nvme" (AIO chunk files), "pinned" (TPU-host pinned
        DRAM buffers — the ZeRO-Offload device=cpu tier, same chunked
        double-buffered step), or "host" (numpy buffers; CPU tests).
        aio_config: the config ``aio`` section — block size + SEPARATE
        read/write queue depths for the two io_uring rings."""
        self.mesh = mesh
        self.storage = storage
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        self.lr = lr
        self.compute_dtype = compute_dtype
        self.pipeline = pipeline
        self.host_inputs = host_inputs  # flatten inputs may live in pinned_host
        self._param_shardings = param_shardings
        self._grad_shardings = grad_shardings

        leaves, self._treedef = jax.tree.flatten(param_template)
        self._shapes = [l.shape for l in leaves]
        self._dtypes = [l.dtype for l in leaves]  # per-leaf (offloaded
        # host stacks stay fp32 while device params are compute_dtype)
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self.num_params = sum(self._sizes)

        ndev = mesh.size
        # chunk length: multiple of the device count so the flat shard is even
        c = max(chunk_elems, ndev)
        c = ((c + ndev - 1) // ndev) * ndev
        self.chunk = c
        self.n_chunks = max(1, math.ceil(self.num_params / c))
        self._padded = self.n_chunks * c

        self._dir = None
        self._aio = self._aio_w = None
        self._buffers = {}  # pinned/host storage: chunk idx -> array
        if storage == "nvme":
            if not nvme_path:
                raise ValueError("storage='nvme' requires nvme_path")
            self._dir = os.path.join(nvme_path,
                                     f"dstpu-optswap-{os.getpid()}")
            os.makedirs(self._dir, exist_ok=True)
            # Two handles: reads (prefetch thread) and writes (writeback
            # thread) overlap; a handle serializes its ops (one ring each),
            # and the config `aio` section sizes the two rings' queue
            # depths independently (read_queue_depth / write_queue_depth).
            self._aio = aio_handle
            self._aio_w = aio_handle
            if aio_handle is None:
                from deepspeed_tpu.ops.aio import (AIOHandle, aio_available,
                                                   report_fallback)
                if aio_available():
                    self._aio = AIOHandle.from_config(aio_config, "read")
                    self._aio_w = AIOHandle.from_config(aio_config, "write")
                else:  # pragma: no cover - only without a toolchain
                    # structured aio_fallback event: the monitor drains it
                    # at the next window boundary — a swapper silently on
                    # synchronous numpy IO is observable, not a log line
                    report_fallback("optimizer-swapper")
        # separate read/write pools: a queued write-behind must never delay
        # the next chunk's prefetch behind it (the old shared 2-worker pool
        # serialized exactly that under load)
        self._pool = ThreadPoolExecutor(max_workers=1) if pipeline else None
        self._wpool = ThreadPoolExecutor(max_workers=1) if pipeline else None
        # two host staging buffers for double-buffered file reads — only the
        # nvme tier stages through numpy (pinned/host return stored arrays)
        self._read_bufs = ([np.empty((_PLANES, c), np.float32)
                            for _ in range(2)]
                           if storage == "nvme" else [None, None])

        self._build_jits()
        where = self._dir if storage == "nvme" else f"{storage} buffers"
        logger.info(
            f"optimizer swap ({storage}): {self.num_params/1e6:.1f}M params "
            f"-> {self.n_chunks} chunks x {c} elems at {where}")

    # ------------------------------------------------------------------
    def _build_jits(self):
        mesh = self.mesh
        c = self.chunk
        flat_sh = NamedSharding(mesh, _flat_spec(mesh))
        repl = NamedSharding(mesh, P())
        sizes, shapes = self._sizes, self._shapes
        treedef = self._treedef
        n_chunks, padded = self.n_chunks, self._padded
        b1, b2, eps = self.b1, self.b2, self.eps
        wd, awm, bc = self.weight_decay, self.adam_w_mode, self.bias_correction
        compute_dtype = self.compute_dtype

        host_inputs = self.host_inputs

        # ---- streamed chunk gather / leaf reassembly (round-2 verdict
        # weakness: the old whole-tree flatten transiently doubled grad HBM
        # and the one-shot unflatten held params + all chunks at once).
        # Segment maps over the fixed leaf order:
        #   chunk ci <- [(leaf li, leaf_offset, len)]
        #   leaf  li <- [(chunk ci, chunk_offset, len)]  (in leaf order)
        self._chunk_segs: List[List] = [[] for _ in range(n_chunks)]
        self._leaf_segs: List[List] = [[] for _ in range(len(sizes))]
        off = 0
        for li, size in enumerate(sizes):
            remaining, lo = size, 0
            while remaining:
                ci = off // c
                take = min(remaining, (ci + 1) * c - off)
                self._chunk_segs[ci].append((li, lo, take))
                self._leaf_segs[li].append((ci, off - ci * c, take))
                off += take
                lo += take
                remaining -= take

        def gather_chunk(ci, *leaves):
            """Assemble grad chunk ci from the relevant leaf slices only
            (HBM transient: one chunk, not the whole flattened tree)."""
            parts = []
            for li, lo, ln in self._chunk_segs[ci]:
                leaf = leaves[li]
                if host_inputs:
                    from jax.memory import Space
                    leaf = jax.device_put(leaf, Space.Device)
                parts.append(jax.lax.dynamic_slice_in_dim(
                    leaf.astype(jnp.float32).reshape(-1), lo, ln))
            flat = (jnp.concatenate(parts) if len(parts) != 1 else parts[0])
            if flat.shape[0] < c:
                flat = jnp.pad(flat, (0, c - flat.shape[0]))
            return jax.lax.with_sharding_constraint(flat, flat_sh)

        # one program per chunk (static slice offsets)
        self._gather_chunk = [
            jax.jit(functools.partial(gather_chunk, ci),
                    out_shardings=flat_sh)
            for ci in range(n_chunks)]

        dtypes = self._dtypes
        out_sh_tree = self._param_shardings
        out_sh_leaves = (jax.tree.leaves(
            out_sh_tree, is_leaf=lambda x: hasattr(x, "spec"))
            if out_sh_tree is not None else [None] * len(sizes))

        def assemble_leaf(li, *chunks):
            """Rebuild param leaf li from the chunk(s) covering it; called
            as soon as the last covering chunk is updated."""
            parts = [jax.lax.dynamic_slice_in_dim(chunks[k], coff, ln)
                     for k, (ci, coff, ln) in enumerate(self._leaf_segs[li])]
            flat = jnp.concatenate(parts) if len(parts) != 1 else parts[0]
            return flat.reshape(shapes[li]).astype(dtypes[li])

        self._assemble_leaf = [
            jax.jit(functools.partial(assemble_leaf, li),
                    out_shardings=out_sh_leaves[li])
            for li in range(len(sizes))]
        # chunk ci -> leaves whose LAST covering chunk is ci (assembled there)
        self._leaves_ending: List[List[int]] = [[] for _ in range(n_chunks)]
        for li in range(len(sizes)):
            self._leaves_ending[self._leaf_segs[li][-1][0]].append(li)

        def tree_sq(*ls):
            if host_inputs:  # pinned_host grads: move before reducing
                from jax.memory import Space
                ls = [jax.device_put(l, Space.Device) for l in ls]
            return sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in ls)

        self._tree_sq = jax.jit(tree_sq, out_shardings=repl)

        def update_chunk(buf, grad, lr_t, step, clip_coef):
            """buf: (3, C) [master, m, v]; grad: (C,) f32 (pre-averaged).
            Returns (new_buf, new_param_chunk[compute_dtype])."""
            master, m, v = buf[0], buf[1], buf[2]
            g = grad * clip_coef
            if wd and not awm:
                g = g + wd * master
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            if bc:
                c1 = 1 - b1 ** step.astype(jnp.float32)
                c2 = 1 - b2 ** step.astype(jnp.float32)
            else:
                c1 = c2 = jnp.float32(1.0)
            upd = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if awm and wd:
                upd = upd + wd * master
            master = master - lr_t * upd
            new_buf = jnp.stack([master, m, v])
            return new_buf, master.astype(compute_dtype)

        buf_sh = NamedSharding(mesh, P(None, *_flat_spec(mesh)))
        self._update_chunk = jax.jit(
            update_chunk,
            in_shardings=(buf_sh, flat_sh, repl, repl, repl),
            out_shardings=(buf_sh, flat_sh),
            donate_argnums=(0,))
        self._buf_sharding = buf_sh
        # some CPU jaxlibs expose no pinned_host memory kind at all — only
        # the pinned storage tier needs it, so degrade to the un-kinded
        # sharding instead of failing every swapper construction (same
        # fallback the infinity executor carries)
        try:
            self._pinned_sharding = NamedSharding(
                mesh, P(None, *_flat_spec(mesh)), memory_kind="pinned_host")
        except (ValueError, TypeError) as e:
            if self.storage == "pinned":
                raise
            logger.warning(f"memory_kind='pinned_host' unsupported on this "
                           f"backend ({e}); un-kinded sharding (no host "
                           "tiering to defeat off-TPU)")
            self._pinned_sharding = buf_sh
        self._init_buf = jax.jit(
            lambda ch: jnp.concatenate(
                [ch[None], jnp.zeros((2, ch.shape[0]), jnp.float32)]),
            out_shardings=buf_sh)


    # ------------------------------------------------------------------
    # file IO
    # ------------------------------------------------------------------
    def _path(self, i: int) -> str:
        return os.path.join(self._dir, f"opt_chunk_{i}.bin")

    def _write_file(self, i: int, host_buf):
        if self.storage == "pinned":
            # device->pinned_host DMA dispatches async; the handle is the
            # storage (nothing crosses the client wire)
            self._buffers[i] = jax.device_put(host_buf, self._pinned_sharding)
        elif self.storage == "host":
            self._buffers[i] = np.ascontiguousarray(
                np.asarray(jax.device_get(host_buf))
                if not isinstance(host_buf, np.ndarray) else host_buf).copy()
        elif self._aio_w is not None:
            # AIOHandle.pwrite carries its own bounded retry + named error
            self._aio_w.pwrite(self._path(i), host_buf)
        else:
            path = self._path(i)

            def do_write():
                rb_faults.io_seam("nvme_write", path)
                host_buf.tofile(path)
            retry_io(do_write, what="optimizer-chunk write", path=path)

    def _read_file(self, i: int, out: np.ndarray = None):
        if self.storage in ("pinned", "host"):
            return self._buffers[i]
        if self._aio is not None:
            return self._aio.pread(self._path(i), out.shape, out.dtype, out=out)
        path = self._path(i)

        def do_read():
            rb_faults.io_seam("nvme_read", path)
            out[...] = np.fromfile(path, np.float32).reshape(out.shape)
            return out
        return retry_io(do_read, what="optimizer-chunk read", path=path)

    # ------------------------------------------------------------------
    def initialize(self, params):
        """Write the initial state: master = params (fp32 upcast), m = v = 0.
        Streams chunk by chunk — full fp32 state never materializes in HBM."""
        buf = np.zeros((_PLANES, self.chunk), np.float32)
        leaves = jax.tree.leaves(params)
        for i in range(self.n_chunks):
            with self.mesh:
                ch = self._gather_chunk[i](*leaves)
            if self.storage == "pinned":
                with self.mesh:
                    self._write_file(i, self._init_buf(ch))
                continue
            buf[0] = np.asarray(jax.device_get(ch))
            buf[1:] = 0.0
            self._write_file(i, buf)

    # ------------------------------------------------------------------
    def step(self, grads, *, lr: float, step_num: int,
             clip: Optional[float] = None, grad_scale: float = 1.0):
        """Apply one AdamW step. grads: averaged grad pytree on device.
        Returns (new_params, grad_norm, overflow: bool). On overflow (fp16)
        nothing is written — the NVMe state is untouched and the caller
        skips the step."""
        with self.mesh:
            gleaves = jax.tree.leaves(grads)

            # global norm (+ overflow detection) straight off the leaves
            total = float(np.asarray(jax.device_get(
                self._tree_sq(*gleaves))))
            if not np.isfinite(total):
                return None, float("nan"), True
            gnorm = math.sqrt(total) / grad_scale
            coef = 1.0 / grad_scale
            if clip and clip > 0 and gnorm > clip:
                coef *= clip / (gnorm + 1e-6)

            lr_t = jnp.float32(lr)
            stepc = jnp.float32(step_num)
            coef_t = jnp.float32(coef)

            # streamed: grad chunks are gathered per chunk, updated param
            # chunks stay alive only until the leaves they cover are
            # reassembled (HBM transient = params + O(leaf), not 2x state)
            out_leaves: List = [None] * len(self._sizes)
            alive: Dict[int, object] = {}
            read_f = None
            writes: List = []   # write-behind futures, double-buffered
            if self.pipeline and self._pool is not None:
                read_f = self._pool.submit(self._read_file, 0, self._read_bufs[0])
            for i in range(self.n_chunks):
                if read_f is not None:
                    host = read_f.result()
                else:
                    host = self._read_file(i, self._read_bufs[i % 2])
                # prefetch next chunk while this one computes on device —
                # the read ring and the write ring are separate handles AND
                # separate pools, so the three-way schedule
                #   read(i+1)  ||  update(i) on device  ||  write(i-1)
                # really runs all three legs concurrently
                if self.pipeline and self._pool is not None and i + 1 < self.n_chunks:
                    read_f = self._pool.submit(
                        self._read_file, i + 1, self._read_bufs[(i + 1) % 2])
                else:
                    read_f = None
                dev_buf = jax.device_put(host, self._buf_sharding)
                new_buf, pchunk = self._update_chunk(
                    dev_buf, self._gather_chunk[i](*gleaves), lr_t, stepc,
                    coef_t)
                alive[i] = pchunk
                for li in self._leaves_ending[i]:
                    cover = [ci for ci, _, _ in self._leaf_segs[li]]
                    out_leaves[li] = self._assemble_leaf[li](
                        *[alive[ci] for ci in cover])
                # retire chunks no unassembled leaf still needs
                needed = {ci for li, segs in enumerate(self._leaf_segs)
                          if out_leaves[li] is None
                          for ci, _, _ in segs if ci <= i}
                for ci in [k for k in alive if k not in needed and k != i]:
                    del alive[ci]
                if self.pipeline and self._wpool is not None:
                    # bound in-flight writes to 2 (double buffer): chunk
                    # i-1's write keeps flowing under chunk i's update
                    # instead of the old drain-before-submit barrier
                    while len(writes) >= 2:
                        writes.pop(0).result()
                    writes.append(self._wpool.submit(self._writeback, i,
                                                     new_buf))
                else:
                    self._writeback(i, new_buf)
            for w in writes:
                w.result()
            new_params = jax.tree.unflatten(self._treedef, out_leaves)
        return new_params, gnorm, False

    def _writeback(self, i: int, dev_buf):
        if self.storage in ("pinned", "host"):
            self._write_file(i, dev_buf)  # pinned: direct device->host DMA
        else:
            self._write_file(i, np.asarray(jax.device_get(dev_buf)))

    # ------------------------------------------------------------------
    # checkpoint integration: the NVMe state is part of the training state
    # ------------------------------------------------------------------
    def export_state(self) -> Dict[str, np.ndarray]:
        """Read all chunks back (for checkpointing). O(state) host memory."""
        out = {}
        for i in range(self.n_chunks):
            buf = np.empty((_PLANES, self.chunk), np.float32)
            got = self._read_file(i, buf)
            if not isinstance(got, np.ndarray):
                got = np.asarray(jax.device_get(got))
            out[f"chunk_{i}"] = got.copy()
        return out

    def import_state(self, chunks: Dict[str, np.ndarray]):
        for i in range(self.n_chunks):
            self._write_file(i, np.ascontiguousarray(chunks[f"chunk_{i}"]))

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._wpool is not None:
            self._wpool.shutdown(wait=True)
            self._wpool = None
        self._buffers.clear()
        if self._dir:
            shutil.rmtree(self._dir, ignore_errors=True)
            # idempotent: the chunk dir is keyed by pid, so a later
            # swapper in this process reuses the same path — a delayed
            # __del__ re-running close() must not rmtree the successor's
            # live directory out from under it
            self._dir = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class XlaHostAdamSwapper:
    """ZeRO-Offload optimizer-on-host, TPU-native flavor: the fp32
    master/m/v tree lives in TPU-host pinned memory and the fused Adam
    sweep runs on the host's cores INSIDE the XLA program
    (``compute_on("device_host")``) — the reference DeepSpeedCPUAdam
    contract (optimizer state never crosses the host<->device bus;
    ``csrc/adam/cpu_adam.cpp:21``) expressed in the compiled graph rather
    than a separate process-side kernel. Per step only 2-byte grads DMA
    down and compute-dtype params DMA up (~4 bytes/param vs the 24+ the
    chunk-streamed tier moves).

    Same interface as HostAdamSwapper (initialize/step/export/import);
    export flattens to the same {master, m, v} flat-f32 layout so the two
    flavors' checkpoints are interchangeable."""

    def __init__(self, param_template, *, mesh, lr=1e-3,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w_mode: bool = True,
                 bias_correction: bool = True, param_shardings=None,
                 compute_dtype=jnp.bfloat16, **_ignored):
        from jax.experimental.compute_on import compute_on
        from deepspeed_tpu.ops.adam import adam_tree_update
        self.mesh = mesh
        self.compute_dtype = compute_dtype
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps, self.wd = eps, weight_decay
        self.awm, self.bc = adam_w_mode, bias_correction
        leaves, self._treedef = jax.tree.flatten(param_template)
        self._shapes = [l.shape for l in leaves]
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self.n = sum(self._sizes)
        self._param_sh = (jax.tree.flatten(param_shardings)[0]
                          if param_shardings is not None
                          else [None] * len(leaves))
        self._host_sh = NamedSharding(mesh, P(), memory_kind="pinned_host")
        host_tree = lambda t: jax.tree.map(  # noqa: E731
            lambda _: self._host_sh, t)
        # fp16's 65504 max can overflow on scaled grads, so the wire is
        # bf16 for every non-f32 compute dtype
        self._wire = (jnp.float32 if compute_dtype == jnp.float32
                      else jnp.bfloat16)
        b1, b2, eps_, wd = self.b1, self.b2, eps, weight_decay
        awm, bc = adam_w_mode, bias_correction
        tmpl = jax.tree.unflatten(self._treedef, leaves)

        def host_step(opt, grads, lr_t, step, coef):
            @compute_on("device_host")
            @jax.jit
            def upd_all(opt, grads, lr_t, step, coef):
                return adam_tree_update(
                    opt, grads, lr_t, step, coef, b1=b1, b2=b2, eps=eps_,
                    wd=wd, awm=awm, bc=bc, out_dtype=compute_dtype)
            return upd_all(opt, grads, lr_t, step, coef)

        opt_tmpl = jax.tree.map(lambda p: {"master": p, "m": p, "v": p},
                                tmpl)
        # params come OUT on the host tier too; the eager device_put in
        # step() moves them up with the engine's shardings (host-region
        # outputs direct to device shardings trip the memory-space checks)
        self._param_sh_tree = jax.tree.unflatten(self._treedef,
                                                 self._param_sh)
        self._host_step = jax.jit(
            host_step,
            in_shardings=(host_tree(opt_tmpl), host_tree(tmpl),
                          self._host_sh, self._host_sh, self._host_sh),
            out_shardings=(host_tree(opt_tmpl), host_tree(tmpl)),
            donate_argnums=(0,))
        self._stage_grads = jax.jit(
            lambda g: jax.tree.map(lambda a: a.astype(self._wire), g),
            out_shardings=host_tree(tmpl))
        self._sq_norm = jax.jit(
            lambda g: sum(jnp.sum(l.astype(jnp.float32) ** 2)
                          for l in jax.tree.leaves(g)))
        self.opt = None
        logger.info(f"host Adam (compute_on): {self.n / 1e6:.1f}M params, "
                    "fp32 state pinned-host-resident, wire dtype "
                    f"{jnp.dtype(self._wire).name}")

    def initialize(self, params):
        init = jax.jit(
            lambda t: jax.tree.map(
                lambda p: {"master": p.astype(jnp.float32),
                           "m": jnp.zeros(p.shape, jnp.float32),
                           "v": jnp.zeros(p.shape, jnp.float32)}, t),
            out_shardings=jax.tree.map(lambda _: self._host_sh, params))
        with self.mesh:
            self.opt = init(params)

    def step(self, grads, *, lr: float, step_num: int,
             clip: Optional[float] = None, grad_scale: float = 1.0):
        with self.mesh:
            sq = float(np.asarray(jax.device_get(self._sq_norm(grads))))
            if not np.isfinite(sq):
                return None, float("nan"), True
            gnorm = math.sqrt(sq) / grad_scale
            coef = 1.0 / grad_scale
            if clip and clip > 0 and gnorm > clip:
                coef *= clip / (gnorm + 1e-6)
            g_host = self._stage_grads(grads)
            lr_h, step_h, coef_h = jax.device_put(
                (jnp.float32(lr), jnp.float32(step_num),
                 jnp.float32(coef)), self._host_sh)
            self.opt, params_host = self._host_step(self.opt, g_host,
                                                    lr_h, step_h, coef_h)
            new_params = jax.tree.map(
                lambda a, s: jax.device_put(a, s) if s is not None
                else jnp.asarray(a), params_host, self._param_sh_tree)
        return new_params, gnorm, False

    def export_state(self) -> Dict[str, np.ndarray]:
        """Flatten to HostAdamSwapper's {master, m, v} flat-f32 layout
        (checkpoints interchangeable across the two flavors). Fetches the
        pinned tree — a checkpoint-path cost, not a step cost."""
        out = {}
        for plane in ("master", "m", "v"):
            host = jax.tree.map(
                lambda o: np.asarray(jax.device_get(o[plane])).reshape(-1),
                self.opt,
                is_leaf=lambda x: isinstance(x, dict) and "master" in x)
            out[plane] = np.concatenate(jax.tree.leaves(host))
        return out

    def import_state(self, state: Dict[str, np.ndarray]):
        planes = {}
        for plane in ("master", "m", "v"):
            flat = state[plane]
            leaves, off = [], 0
            for size, shape in zip(self._sizes, self._shapes):
                leaves.append(flat[off:off + size].reshape(shape)
                              .astype(np.float32))
                off += size
            planes[plane] = leaves
        opt_leaves = [{"master": m_, "m": a, "v": b} for m_, a, b in
                      zip(planes["master"], planes["m"], planes["v"])]
        tree = jax.tree.unflatten(self._treedef, opt_leaves)
        self.opt = jax.device_put(tree, self._host_sh)

    def close(self):
        self.opt = None


class HostAdamSwapper:
    """ZeRO-Offload with the optimizer ON the host: fp32 master/m/v live in
    host RAM and the native fused CPU-Adam (ops/cpu_adam.py, reference:
    DeepSpeedCPUAdam over csrc/adam/cpu_adam.cpp) updates them in place.
    Per step only compute-dtype grads cross down and params cross up —
    4 bytes/param instead of the 28 the state-streaming tier moves.

    Same interface as NVMeOptimizerSwapper (initialize/step/export/import).
    The right tier on a real TPU-VM where this process runs on the TPU
    host; through a remote relay the grad/param hop crosses the wire, so it
    stays opt-in (offload_optimizer.use_cpu_adam)."""

    def __init__(self, param_template, *, mesh, lr=1e-3,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w_mode: bool = True,
                 bias_correction: bool = True, param_shardings=None,
                 compute_dtype=jnp.bfloat16, optim: str = "adam",
                 **_ignored):
        self.mesh = mesh
        self.compute_dtype = compute_dtype
        self.lr = lr
        self.optim = optim
        leaves, self._treedef = jax.tree.flatten(param_template)
        self._shapes = [l.shape for l in leaves]
        self._sizes = [int(np.prod(s)) for s in self._shapes]
        self._offsets = np.cumsum([0] + self._sizes).tolist()
        self.n = sum(self._sizes)
        self._param_sh = (jax.tree.flatten(param_shardings)[0]
                          if param_shardings is not None
                          else [None] * len(leaves))
        if optim == "adagrad":
            # host Adagrad tier (reference: DeepSpeedCPUAdagrad over
            # csrc/adagrad/cpu_adagrad.cpp) — CPUAdam-compatible interface
            from deepspeed_tpu.ops.cpu_adagrad import CPUAdagrad
            self.cpu = CPUAdagrad(self.n, lr=lr, eps=eps,
                                  weight_decay=weight_decay)
        else:
            from deepspeed_tpu.ops.cpu_adam import CPUAdam
            self.cpu = CPUAdam(self.n, lr=lr, betas=betas, eps=eps,
                               weight_decay=weight_decay,
                               adamw_mode=adam_w_mode,
                               bias_correction=bias_correction)
        self._bf16 = compute_dtype == jnp.bfloat16
        self._f16 = compute_dtype == jnp.float16
        wire_np = (np.uint16 if self._bf16
                   else np.float16 if self._f16 else np.float32)
        self._gbuf = np.empty(self.n, wire_np)
        self._pbuf = np.empty(self.n, np.uint16 if self._bf16 else np.float32)
        if self._f16:
            # f16 wire: widen grads to f32 for the native Adam, narrow the
            # updated params back to f16 — keeps transfers at 2 bytes/param
            # and the returned leaf dtype stable (no f32 drift under fp16).
            self._g32 = np.empty(self.n, np.float32)
            self._p16 = np.empty(self.n, np.float16)
        # per-leaf device-side cast to the wire dtype (bits for bf16)
        if self._bf16:
            self._cast = jax.jit(lambda g: jax.lax.bitcast_convert_type(
                g.astype(jnp.bfloat16), jnp.uint16))
        elif self._f16:
            self._cast = jax.jit(lambda g: g.astype(jnp.float16))
        else:
            self._cast = jax.jit(lambda g: g.astype(jnp.float32))
        logger.info(f"host CPU-{optim.capitalize()}: {self.n / 1e6:.1f}M "
                    "params, fp32 state host-resident, wire dtype "
                    f"{'bf16' if self._bf16 else 'f16' if self._f16 else 'f32'}")

    def initialize(self, params):
        off = 0
        for leaf in jax.tree.leaves(params):
            a = np.asarray(jax.device_get(leaf), np.float32).reshape(-1)
            self.cpu.master[off:off + a.size] = a
            off += a.size

    def step(self, grads, *, lr: float, step_num: int,
             clip: Optional[float] = None, grad_scale: float = 1.0):
        import ml_dtypes
        gleaves = jax.tree.leaves(grads)
        futs = [self._cast(g) for g in gleaves]   # async device casts
        for fut, off, size in zip(futs, self._offsets, self._sizes):
            np.copyto(self._gbuf[off:off + size],
                      np.asarray(jax.device_get(fut)).reshape(-1))
        if self._f16:
            np.copyto(self._g32, self._gbuf)   # widen on host
            gflat = self._g32
        else:
            gflat = self._gbuf
        sq = self.cpu.sq_norm(gflat)
        if not np.isfinite(sq):
            return None, float("nan"), True
        gnorm = math.sqrt(sq) / grad_scale
        coef = 1.0 / grad_scale
        if clip and clip > 0 and gnorm > clip:
            coef *= clip / (gnorm + 1e-6)
        self.cpu.step(gflat, step_num, lr=lr, grad_scale=coef,
                      out=self._pbuf)
        if self._f16:
            np.copyto(self._p16, self._pbuf)   # narrow for the wire
        out_leaves = []
        for off, size, shape, sh in zip(self._offsets, self._sizes,
                                        self._shapes, self._param_sh):
            if self._f16:
                seg = self._p16[off:off + size].reshape(shape)
            else:
                seg = self._pbuf[off:off + size].reshape(shape)
            if self._bf16:
                seg = seg.view(ml_dtypes.bfloat16)
            arr = (jax.device_put(seg, sh) if sh is not None
                   else jnp.asarray(seg))
            out_leaves.append(arr)
        return jax.tree.unflatten(self._treedef, out_leaves), gnorm, False

    def export_state(self) -> Dict[str, np.ndarray]:
        if self.optim == "adagrad":
            return {"master": self.cpu.master.copy(),
                    "accum": self.cpu.accum.copy()}
        return {"master": self.cpu.master.copy(), "m": self.cpu.m.copy(),
                "v": self.cpu.v.copy()}

    def import_state(self, state: Dict[str, np.ndarray]):
        np.copyto(self.cpu.master, state["master"])
        if self.optim == "adagrad":
            np.copyto(self.cpu.accum, state["accum"])
        else:
            np.copyto(self.cpu.m, state["m"])
            np.copyto(self.cpu.v, state["v"])

    def close(self):
        pass
