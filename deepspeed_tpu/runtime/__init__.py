from deepspeed_tpu.runtime.engine import Engine, initialize
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.runtime import fp16
from deepspeed_tpu.runtime import zero
from deepspeed_tpu.runtime import checkpointing
