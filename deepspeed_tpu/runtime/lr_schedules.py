"""LR schedules as pure functions step -> lr.

Reference: ``deepspeed/runtime/lr_schedules.py:17-20`` — LRRangeTest, OneCycle,
WarmupLR, WarmupDecayLR (same names + parameter keys). A schedule here is a
callable usable inside jit (step may be a traced int32), which is why these
are closures over jnp math instead of stateful scheduler objects.
"""

import math
from typing import Callable, Optional

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
COSINE = "CosineAnnealing"  # TPU-native addition (commonly needed, absent in ref)

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, COSINE]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)
    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / max(1, warmup_num_steps), 0.0, 1.0)
        if warmup_type == "log":
            # matches reference: min + (max-min) * log1p-normalized progress
            gamma = jnp.log1p(frac * (math.e - 1.0))
        else:
            gamma = frac
        warm = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma
        return jnp.where(step < warmup_num_steps, warm, warmup_max_lr)
    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> Schedule:
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay_frac = jnp.clip(
            (total_num_steps - step) / max(1.0, total_num_steps - warmup_num_steps),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps, warm(step), warmup_max_lr * decay_frac)
    return schedule


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0,
              **_) -> Schedule:
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((step - cycle_first_step_size) / max(1, second), 0.0, 1.0)
        in_cycle_lr = jnp.where(
            step <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(0.0, (step - total_cycle) / decay_step_size)
            decayed = cycle_min_lr / (1.0 + decay_steps * decay_lr_rate)
            return jnp.where(step > total_cycle, decayed, in_cycle_lr)
        return in_cycle_lr
    return schedule


def cosine_annealing(max_lr: float, total_num_steps: int,
                     warmup_num_steps: int = 0, min_lr: float = 0.0, **_) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = max_lr * step / max(1, warmup_num_steps)
        progress = jnp.clip((step - warmup_num_steps) /
                            max(1, total_num_steps - warmup_num_steps), 0.0, 1.0)
        cos = min_lr + 0.5 * (max_lr - min_lr) * (1.0 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_num_steps, warm, cos)
    return schedule


_FACTORIES = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    COSINE: cosine_annealing,
}


def get_scheduler(name: Optional[str], params: dict) -> Optional[Schedule]:
    if name is None:
        return None
    if name not in _FACTORIES:
        raise ValueError(f"unknown scheduler '{name}'; valid: {VALID_LR_SCHEDULES}")
    return _FACTORIES[name](**params)
