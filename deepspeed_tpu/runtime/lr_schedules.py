"""LR schedules as pure functions step -> lr.

Reference: ``deepspeed/runtime/lr_schedules.py:17-20`` — LRRangeTest, OneCycle,
WarmupLR, WarmupDecayLR (same names + parameter keys). A schedule here is a
callable usable inside jit (step may be a traced int32), which is why these
are closures over array math instead of stateful scheduler objects.

Dual-mode evaluation: inside the jitted step the optimizer calls the schedule
with a traced int32 and the math runs in jnp; host callers (``engine.get_lr``
at log boundaries, the NVMe swapper's per-step lr) pass a plain Python int
and the SAME closure evaluates in numpy — a float comes back with zero device
work, so a log-boundary ``get_lr()`` cannot stall the async step pipeline.
"""

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

Schedule = Callable[[jnp.ndarray], jnp.ndarray]

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
COSINE = "CosineAnnealing"  # TPU-native addition (commonly needed, absent in ref)

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, COSINE]


def _xp(step):
    """jnp for traced/device inputs (tracers are jax.Array instances), numpy
    for host ints/floats — the one dispatch point for dual-mode schedules."""
    return jnp if isinstance(step, jax.Array) else np


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    def schedule(step):
        xp = _xp(step)
        step = xp.asarray(step, xp.float32)
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = xp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)
    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> Schedule:
    def schedule(step):
        xp = _xp(step)
        step = xp.asarray(step, xp.float32)
        frac = xp.clip(step / max(1, warmup_num_steps), 0.0, 1.0)
        if warmup_type == "log":
            # matches reference: min + (max-min) * log1p-normalized progress
            gamma = xp.log1p(frac * (math.e - 1.0))
        else:
            gamma = frac
        warm = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma
        return xp.where(step < warmup_num_steps, warm, warmup_max_lr)
    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> Schedule:
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        xp = _xp(step)
        step = xp.asarray(step, xp.float32)
        decay_frac = xp.clip(
            (total_num_steps - step) / max(1.0, total_num_steps - warmup_num_steps),
            0.0, 1.0)
        return xp.where(step < warmup_num_steps, warm(step), warmup_max_lr * decay_frac)
    return schedule


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0,
              **_) -> Schedule:
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        xp = _xp(step)
        step = xp.asarray(step, xp.float32)
        up = xp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down = xp.clip((step - cycle_first_step_size) / max(1, second), 0.0, 1.0)
        in_cycle_lr = xp.where(
            step <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down)
        if decay_step_size > 0:
            decay_steps = xp.maximum(0.0, (step - total_cycle) / decay_step_size)
            decayed = cycle_min_lr / (1.0 + decay_steps * decay_lr_rate)
            return xp.where(step > total_cycle, decayed, in_cycle_lr)
        return in_cycle_lr
    return schedule


def cosine_annealing(max_lr: float, total_num_steps: int,
                     warmup_num_steps: int = 0, min_lr: float = 0.0, **_) -> Schedule:
    def schedule(step):
        xp = _xp(step)
        step = xp.asarray(step, xp.float32)
        warm = max_lr * step / max(1, warmup_num_steps)
        progress = xp.clip((step - warmup_num_steps) /
                           max(1, total_num_steps - warmup_num_steps), 0.0, 1.0)
        cos = min_lr + 0.5 * (max_lr - min_lr) * (1.0 + xp.cos(xp.pi * progress))
        return xp.where(step < warmup_num_steps, warm, cos)
    return schedule


_FACTORIES = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    COSINE: cosine_annealing,
}


def get_scheduler(name: Optional[str], params: dict) -> Optional[Schedule]:
    if name is None:
        return None
    if name not in _FACTORIES:
        raise ValueError(f"unknown scheduler '{name}'; valid: {VALID_LR_SCHEDULES}")
    return _FACTORIES[name](**params)
