"""Accelerator abstraction.

Reference: ``accelerator/abstract_accelerator.py:7`` (~60-method ABC over device
management, streams, events, memory, RNG, tensor factories) and
``accelerator/real_accelerator.py:34,52`` (global get/set singleton).

TPU-native re-design: XLA owns scheduling, so the stream/event surface of the
reference is intentionally absent — async dispatch plus buffer donation is the
idiomatic equivalent, and the few callers that genuinely need ordering use
``synchronize()``. What remains is the part that is real on TPU: device
enumeration, platform naming, memory stats, RNG seeding, default dtypes, and
the communication-backend name (ICI/DCN via XLA collectives instead of NCCL).
"""

import os
from typing import List, Optional

import numpy as np


class Accelerator:
    """Base accelerator over JAX device APIs; concrete for any JAX platform."""

    def __init__(self, platform: Optional[str] = None):
        import jax
        self._jax = jax
        self._platform = platform or jax.default_backend()

    # --- naming -----------------------------------------------------------
    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._platform
        return f"{self._platform}:{device_index}"

    @property
    def platform(self) -> str:
        return self._platform

    def is_available(self) -> bool:
        try:
            return len(self.devices()) > 0
        except RuntimeError:
            return False

    def communication_backend_name(self) -> str:
        """'xla' — collectives compile onto ICI/DCN; reference returns 'nccl'
        (``accelerator/cuda_accelerator.py``)."""
        return "xla"

    # --- devices ----------------------------------------------------------
    def devices(self) -> List:
        return self._jax.devices(self._platform)

    def local_devices(self) -> List:
        return self._jax.local_devices(backend=self._platform)

    def device_count(self) -> int:
        return len(self.devices())

    def local_device_count(self) -> int:
        return len(self.local_devices())

    def process_index(self) -> int:
        return self._jax.process_index()

    def process_count(self) -> int:
        return self._jax.process_count()

    def current_device(self):
        return self.local_devices()[0]

    def synchronize(self, device=None) -> None:
        """Block until all dispatched work is complete (reference:
        ``torch.cuda.synchronize``)."""
        self._jax.effects_barrier()

    # --- memory -----------------------------------------------------------
    def memory_stats(self, device=None) -> dict:
        from deepspeed_tpu.utils.memory import device_memory_stats
        return device_memory_stats(device or self.current_device())

    def memory_allocated(self, device=None) -> int:
        device = device or self.current_device()
        try:
            return (device.memory_stats() or {}).get("bytes_in_use", 0)
        except Exception:
            return 0

    def max_memory_allocated(self, device=None) -> int:
        device = device or self.current_device()
        try:
            return (device.memory_stats() or {}).get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    def total_memory(self, device=None) -> int:
        device = device or self.current_device()
        try:
            return (device.memory_stats() or {}).get("bytes_limit", 0)
        except Exception:
            return 0

    def hbm_bytes(self, device=None) -> int:
        """Per-device HBM capacity. Prefers live ``memory_stats``; falls back
        to a device-kind table because some transports (e.g. the axon relay)
        return no stats. Used by bench auto-sizing and the autotuner."""
        limit = self.total_memory(device)
        if limit:
            return limit
        GiB = 1 << 30
        kind = self.device_kind().lower()
        table = {
            "v5 lite": 16 * GiB, "v5e": 16 * GiB, "v5litepod": 16 * GiB,
            "v5p": 95 * GiB, "v6 lite": 32 * GiB, "v6e": 32 * GiB,
            "v4": 32 * GiB, "v3": 16 * GiB, "v2": 8 * GiB,
        }
        for key, val in table.items():
            if key in kind:
                return val
        if self._platform == "cpu":
            return 8 * GiB
        return 16 * GiB  # conservative default for unknown TPU kinds

    def available_memory(self, device=None) -> int:
        return max(0, self.total_memory(device) - self.memory_allocated(device))

    def empty_cache(self) -> None:
        """No-op: XLA's BFC allocator manages HBM; live buffers are freed by GC."""

    # --- RNG --------------------------------------------------------------
    def manual_seed(self, seed: int):
        """Return a root PRNG key. JAX threads explicit keys instead of global
        RNG state (reference mutates ``torch.cuda`` RNG)."""
        return self._jax.random.PRNGKey(seed)

    def default_generator(self, seed: int = 0):
        return self._jax.random.PRNGKey(seed)

    # --- dtypes -----------------------------------------------------------
    def preferred_dtype(self):
        import jax.numpy as jnp
        return jnp.bfloat16 if self._platform == "tpu" else jnp.float32

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # --- HLO/interconnect hints ------------------------------------------
    def device_kind(self) -> str:
        devs = self.local_devices()
        return devs[0].device_kind if devs else "unknown"

    def peak_flops_per_device(self, dtype: str = "bf16") -> float:
        """Best-effort peak matmul FLOPs for MFU math; see BASELINE.md."""
        kind = self.device_kind().lower()
        table = {
            # chip kind substring -> bf16 peak FLOPs
            "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
            "v5p": 459e12, "v4": 275e12, "v3": 123e12, "v6": 918e12,
        }
        for key, val in table.items():
            if key in kind:
                return val
        if self._platform == "cpu":
            return 1e11
        return 197e12

    def interconnect_bytes_per_sec(self) -> float:
        """Best-effort aggregate per-chip ICI bandwidth (bytes/sec), used to
        PRICE exposed collective bytes into modeled wire time
        (telemetry ``exposed_comm_ms``). Rough published per-chip aggregates
        — a modeling constant for trend tracking, not a measured number."""
        kind = self.device_kind().lower()
        table = {
            # chip kind substring -> aggregate ICI bytes/sec
            "v5 lite": 2.0e11, "v5e": 2.0e11, "v5litepod": 2.0e11,
            "v5p": 6.0e11, "v4": 3.0e11, "v3": 2.0e11, "v6": 4.5e11,
        }
        for key, val in table.items():
            if key in kind:
                return val
        if self._platform == "cpu":
            return 1e10
        return 2.0e11

    def hbm_bytes_per_sec(self) -> float:
        """Best-effort per-chip HBM bandwidth (bytes/sec). Used with
        ``peak_flops_per_device`` as the roofline balance point when the
        perf doctor classifies a traced bucket compute- vs memory-bound.
        Published chip numbers — a modeling constant, not a measurement."""
        kind = self.device_kind().lower()
        table = {
            # chip kind substring -> HBM bytes/sec
            "v5 lite": 8.2e11, "v5e": 8.2e11, "v5litepod": 8.2e11,
            "v5p": 2.77e12, "v4": 1.2e12, "v3": 9.0e11, "v2": 7.0e11,
            "v6": 1.6e12,
        }
        for key, val in table.items():
            if key in kind:
                return val
        if self._platform == "cpu":
            return 5e10
        return 8.2e11

    def pin_memory(self, array):
        """Host staging; JAX host buffers are already DMA-capable — identity."""
        return array

    def on_device(self, array, device=None):
        return self._jax.device_put(array, device or self.current_device())


class TPU_Accelerator(Accelerator):
    def __init__(self):
        super().__init__(platform=None)


class CPU_Accelerator(Accelerator):
    def __init__(self):
        super().__init__(platform="cpu")

    def peak_flops_per_device(self, dtype: str = "bf16") -> float:
        return 1e11


_ACCELERATOR: Optional[Accelerator] = None


def get_accelerator() -> Accelerator:
    """Global accelerator singleton (reference:
    ``accelerator/real_accelerator.py:34``). Honors DSTPU_ACCELERATOR=cpu|tpu."""
    global _ACCELERATOR
    if _ACCELERATOR is None:
        forced = os.environ.get("DSTPU_ACCELERATOR", "").lower()
        if forced == "cpu":
            _ACCELERATOR = CPU_Accelerator()
        else:
            _ACCELERATOR = Accelerator()
    return _ACCELERATOR


def set_accelerator(accel: Accelerator) -> None:
    global _ACCELERATOR
    _ACCELERATOR = accel
