"""Collective scheduling: deferred gradient sync + hierarchical reduction.

Reference: ``runtime/zero/stage_1_and_2.py`` — DeepSpeed's headline ZeRO
throughput comes as much from *when* collectives run as from sharding
itself: ``overlap_comm`` overlaps grad reduction with backward compute,
``no_sync`` defers it across accumulation boundaries, and the hierarchical
all-reduce splits a flat ring into intra-node + inter-node phases.

TPU-native design: GSPMD owns collective *placement*, so scheduling policy
is expressed structurally —

* **deferred sync** (``comm.deferred_grad_sync``): the microbatch grad
  accumulation runs inside a ``shard_map`` that is *manual* over the
  ``data`` mesh axis (every other axis stays auto/GSPMD). Per-device grads
  accumulate locally across the whole scan — no data-axis collective can
  exist inside the loop because the axis is manual and nothing asks for
  one — and a single explicit ``psum``/``psum_scatter`` at the step
  boundary produces exactly the reduction the eager path spreads over every
  microbatch. Stage-1/2 dp-sync collective counts become independent of
  ``gradient_accumulation_steps`` (DeepSpeed ``no_sync`` semantics).

* **hierarchical reduction** (``comm.hierarchical_grad_reduce``): on
  ``data x fsdp`` meshes the dp grad mean decomposes into an fsdp-axis
  reduce-scatter (inner, fast ICI ring, full payload) followed by a
  data-axis all-reduce of the *sharded* buffer (outer ring, 1/fsdp of the
  bytes). Expressed as sharding-constraint hints: the accumulator is pinned
  to an fsdp-sharded spec before the data-axis reduction, so GSPMD must
  realize the two phases separately. The analysis census pins the result
  exactly for the MULTICHIP mesh plans.

Everything here is pure spec/tree surgery plus the in-``shard_map``
boundary reduction; the engine wires it into the dense GSPMD step, the
fused K-step program, and (trivially — it is already deferred by
construction) the 1-bit shard_map step.
"""

from typing import Optional, Tuple

import jax
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"


def shard_map_compat(f, mesh, *, in_specs, out_specs, manual_axes):
    """Partial-auto shard_map across jax versions: `jax.shard_map` with
    axis_names (>= 0.6 spelling) or the experimental module with
    `auto=` (the 0.4.x spelling). Only `manual_axes` become manual; every
    other mesh axis stays auto — GSPMD keeps partitioning the body over
    them (param all-gathers, TP reductions, fsdp constraints)."""
    import jax
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             axis_names=set(manual_axes), check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm
        auto = frozenset(mesh.axis_names) - frozenset(manual_axes)
        return _sm(f, mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False, auto=auto)


def _entries(spec: P):
    """PartitionSpec -> list of per-dim axis tuples."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(entry))
        else:
            out.append((entry,))
    return out


def _from_entries(entries) -> P:
    out = [tuple(e) if len(e) > 1 else (e[0] if e else None) for e in entries]
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def spec_axes(spec: P):
    """All mesh axis names a spec references."""
    return {a for e in _entries(spec) for a in e}


def axis_dim(spec: P, axis: str) -> Optional[int]:
    """Dim index carrying `axis`, or None."""
    for i, e in enumerate(_entries(spec)):
        if axis in e:
            return i
    return None


def drop_axis(spec: P, axis: str) -> P:
    """Remove every reference to `axis` from a spec (the LOCAL view of a
    tensor inside a region that is manual over `axis`)."""
    return _from_entries([tuple(a for a in e if a != axis)
                          for e in _entries(spec)])


def local_tree(spec_tree, axis: str = DATA_AXIS):
    """grad_specs -> their local (manual-over-`axis`) counterparts."""
    return jax.tree.map(lambda s: drop_axis(s, axis), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def hierarchical_spec(grad_spec: P, shape: Tuple[int, ...], plan) -> P:
    """Intermediate fsdp-sharded spec for one grad leaf: the buffer the
    data-axis phase of the hierarchical reduction operates on.

    Leaves already fsdp-sharded (stage 3) keep their spec — the
    decomposition is inherent there. Otherwise shard the largest dim that
    is unsharded and divisible by the fsdp degree; leaves where nothing
    divides stay as-is (tiny tensors ride the flat reduction).
    """
    if plan.fsdp <= 1 or FSDP_AXIS in spec_axes(grad_spec):
        return grad_spec
    sizes = plan.axis_sizes()
    entries = _entries(grad_spec)
    while len(entries) < len(shape):
        entries.append(())
    best_dim, best_size = -1, 0
    for i, dim in enumerate(shape):
        denom = int(np.prod([sizes.get(a, 1) for a in entries[i]])) \
            if entries[i] else 1
        local = dim // denom if denom and dim % denom == 0 else 0
        if local and local % plan.fsdp == 0 and local > best_size:
            best_dim, best_size = i, local
    if best_dim < 0:
        return grad_spec
    entries[best_dim] = entries[best_dim] + (FSDP_AXIS,)
    return _from_entries(entries)


def hierarchical_tree(grad_specs, shape_tree, plan):
    return jax.tree.map(
        lambda s, sh: hierarchical_spec(s, tuple(sh), plan),
        grad_specs, shape_tree, is_leaf=lambda x: isinstance(x, P))


def deferred_supported(plan) -> Tuple[bool, str]:
    """Whether the deferred-sync shard_map region composes with this mesh.

    The region is manual over `data` only — params are never data-sharded,
    so they enter replicated and the model body runs unmodified (fsdp/
    tensor stay auto: GSPMD still inserts the per-use param all-gathers and
    TP reductions inside). Axes that restructure the step itself can't
    nest: pipeline's manual region, ring attention's seq collectives, and
    MoE's expert-data routing.
    """
    if plan.pipe > 1:
        return False, "pipeline parallelism wraps the step in its own " \
                      "manual mesh region"
    if plan.seq > 1:
        return False, "ring attention's seq-axis collectives cannot nest " \
                      "inside a manual-data region"
    if plan.expert > 1:
        return False, "expert-data routing folds the data axis at dispatch " \
                      "time"
    return True, ""


def boundary_reduce(grads, grad_specs, plan, *, mean: bool = True):
    """The ONE data-axis reduction of the deferred path, applied to the
    locally-accumulated grad tree inside the manual-over-`data` region.

    Per leaf: grad specs carrying `data` on a dim get a ``psum_scatter``
    (reduce-scatter) on that dim — the output lands exactly where ZeRO
    stage >= 2 wants it; replicated-over-data leaves get a ``psum``
    (all-reduce). ``mean=True`` folds the 1/data normalization in after the
    sum (an exponent-only scale for power-of-two meshes), matching the
    eager path's global-mean gradient bit-for-bit when the sums themselves
    are exact.
    """
    inv = np.float32(1.0 / plan.data)

    def one(g, spec):
        dim = axis_dim(spec, DATA_AXIS)
        if dim is None:
            g = lax.psum(g, DATA_AXIS)
        else:
            g = lax.psum_scatter(g, DATA_AXIS, scatter_dimension=dim,
                                 tiled=True)
        return g * inv if mean else g

    # grad_sync scope: the perf doctor's trace join attributes the boundary
    # collectives' device time to the grad-sync phase by this op_name path
    with jax.named_scope("grad_sync"):
        return jax.tree.map(one, grads, grad_specs,
                            is_leaf=lambda x: isinstance(x, P))


def manual_out_spec(grad_specs):
    """shard_map out_specs for the reduced grad tree: only the manual
    (`data`) placement is named; auto-axis sharding (fsdp/tensor) rides
    through from the constraints inside the body."""
    def one(spec):
        dim = axis_dim(spec, DATA_AXIS)
        if dim is None:
            return P()
        return P(*([None] * dim + [DATA_AXIS]))
    return jax.tree.map(one, grad_specs, is_leaf=lambda x: isinstance(x, P))
