from deepspeed_tpu.comm.comm import (
    init_distributed, is_initialized, get_world_size, get_rank,
    get_local_rank, get_device_count, get_local_device_count, barrier,
    all_reduce, all_gather, reduce_scatter, all_to_all, ppermute, broadcast,
    psum, pmean, pmax,
    log_summary, comms_logger,
)
from deepspeed_tpu.comm import schedule
