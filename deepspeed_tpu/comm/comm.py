"""Communication facade.

Reference: ``deepspeed/comm/comm.py`` — ``init_distributed:530`` (env/MPI
rendezvous), every collective wrapped by ``timed_op:108`` for the comms logger,
``all_reduce:448``, ``all_gather:225``, ``reduce_scatter_fn:243``,
``all_to_all_single:328``, ``barrier:397``, ``log_summary:413``.

TPU-native design: collectives are *compiled* — `jax.lax.psum` etc. inside a
jitted/shard_mapped region lower to XLA collectives on ICI/DCN. Two
consequences vs the reference:

1. There is no eager per-call wall-clock to time; the comms logger records
   trace-time counts + message sizes, and wall-clock attribution comes from
   `jax.profiler` traces (SURVEY §5 "comm logging via profiler
   instrumentation").
2. Process groups are mesh axis names, not opaque handles. Every collective
   here takes `axis: str | tuple[str, ...]`.

Multi-host bootstrap is `jax.distributed.initialize` (the reference's env://
rendezvous equivalent); single-process multi-device needs no init at all.
"""

import os
import threading
import time
from collections import defaultdict
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils.logging import logger

Axis = Union[str, Sequence[str]]

_INITIALIZED = False


# --------------------------------------------------------------------------
# Comms logger (reference: utils/comms_logging.py:58 + comm/comm.py:108 timed_op)
# --------------------------------------------------------------------------

class CommsLogger:
    """Records collective calls at trace time: op name, axis, bytes.

    `record_host` additionally records wall-clock for *host-blocking* comm
    (checkpoint broadcast, init barriers) where eager timing is meaningful.
    """

    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_ops = set()
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        # rebinding races a concurrent record() (an in-flight increment can
        # land on the dropped maps, or summary() can read a half-swapped
        # pair) — swap all three under the same lock record() takes
        with self._lock:
            self.counts = defaultdict(int)
            self.bytes = defaultdict(int)
            self.host_ms = defaultdict(float)

    def configure(self, enabled=True, verbose=False, prof_ops=()):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_ops = set(prof_ops or ())

    def record(self, op: str, axis, nbytes: int):
        if not self.enabled:
            return
        if self.prof_ops and op not in self.prof_ops:
            return
        key = f"{op}[{axis}]"
        with self._lock:
            self.counts[key] += 1
            self.bytes[key] += nbytes
        if self.verbose:
            logger.info(f"comm: {key} msg_size={nbytes}")

    def record_host(self, op: str, ms: float):
        if self.enabled:
            with self._lock:
                self.host_ms[op] += ms

    def summary(self) -> str:
        with self._lock:
            counts = dict(self.counts)
            nbytes = dict(self.bytes)
            host = dict(self.host_ms)
        lines = ["comm op                          count      total MB"]
        for key in sorted(counts):
            lines.append(f"{key:<32} {counts[key]:>6} {nbytes[key] / 1e6:>12.2f}")
        for key in sorted(host):
            lines.append(f"{key:<32} host_ms={host[key]:.1f}")
        return "\n".join(lines)

    def census_lines(self, census) -> list:
        """Format a graft-lint collective census ({kind: {count, bytes}})
        as summary rows. These are the collectives GSPMD *inserted* into
        the compiled step (all-gathers for ZeRO-3 params, reduce-scatters
        for grad sharding, ...) — invisible to `record`, which only sees
        explicit jax-level calls at trace time."""
        lines = []
        for kind in sorted(census):
            c = census[kind]
            lines.append(f"gspmd/{kind:<26} {c.get('count', 0):>6} "
                         f"{c.get('bytes', 0) / 1e6:>12.2f}")
        return lines

    def census_events(self, census, step: int):
        """Monitor-ready triples of the GSPMD census (per compiled step):
        ``comm/gspmd/<kind>/{count,bytes}``."""
        out = []
        for kind in sorted(census):
            c = census[kind]
            out.append((f"comm/gspmd/{kind}/count",
                        float(c.get("count", 0)), step))
            out.append((f"comm/gspmd/{kind}/bytes",
                        float(c.get("bytes", 0)), step))
        return out

    def events(self, step: int):
        """Monitor-ready ``(name, value, step)`` triples of the running
        totals: per-op ``comm/<op>[axis]/{count,bytes}`` plus
        ``comm/host_ms/<op>`` for host-blocking comm. Counts/bytes are
        recorded at TRACE time (a jitted collective is ONE static site
        however many times the compiled program runs); the engine fans these
        out at steps_per_print boundaries, so totals grow only when new
        programs are traced — the per-execution wire model is the telemetry
        static x runtime join."""
        with self._lock:
            counts = dict(self.counts)
            nbytes = dict(self.bytes)
            host = dict(self.host_ms)
        out = []
        for key in sorted(counts):
            out.append((f"comm/{key}/count", float(counts[key]), step))
            out.append((f"comm/{key}/bytes", float(nbytes[key]), step))
        for op in sorted(host):
            out.append((f"comm/host_ms/{op}", float(host[op]), step))
        return out


comms_logger = CommsLogger()


def log_summary(monitor=None, step: Optional[int] = None,
                engine=None) -> str:
    """Reference: ``deepspeed.comm.log_summary`` (comm/comm.py:413). With a
    ``monitor`` (e.g. ``engine.monitor``), the totals also fan out as
    monitor events instead of log-only text — pass ``step`` (e.g.
    ``engine.global_steps``): wandb silently drops events whose step is
    lower than what it already logged.

    With ``engine=``, the summary also reports the graft-lint collective
    census of the engine's compiled train step — the GSPMD-inserted
    all-gather/reduce-scatter kinds+bytes the trace-time `record` hook can
    never see (the reference's per-collective accounting wraps every torch
    call at comm/comm.py:108; on TPU the partitioner inserts the real
    collectives at compile time, so the census is read from the scheduled
    HLO via the telemetry static join). Costs nothing in steady state: the
    static join is computed once, lazily, off the hot path."""
    msg = comms_logger.summary()
    census = None
    if engine is not None:
        try:
            static = engine._tel_static_cost(wait=True)
            census = (static or {}).get("census") or None
        except Exception as e:  # noqa: BLE001 — summary must never raise
            logger.debug(f"comm.log_summary: census unavailable: {e!r}")
        if census:
            msg += ("\ngspmd census (compiled train step)     count"
                    "      total MB\n")
            msg += "\n".join(comms_logger.census_lines(census))
        if step is None:
            step = getattr(engine, "global_steps", None)
    logger.info("\n" + msg)
    if monitor is not None and getattr(monitor, "enabled", False):
        if step is None:
            logger.warning("comm.log_summary(monitor=...) without step= — "
                           "events land on step 0 and step-ordered sinks "
                           "(wandb) may drop them; pass "
                           "step=engine.global_steps")
            step = 0
        monitor.write_events(comms_logger.events(step))
        if census:
            monitor.write_events(comms_logger.census_events(census, step))
    return msg


def _nbytes(x) -> int:
    try:
        return sum(int(v.size) * v.dtype.itemsize for v in jax.tree.leaves(x))
    except Exception:
        return 0


# --------------------------------------------------------------------------
# Init / world queries
# --------------------------------------------------------------------------

def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto_mpi_discovery: bool = True,
                     timeout_s: int = 300,
                     **_ignored) -> None:
    """Initialize multi-host JAX if needed (reference: comm/comm.py:530).

    Single-process (incl. single-process multi-device) needs nothing. For
    multi-host, honors explicit args, then env vars
    (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID set by our launcher, or the
    reference-style RANK/WORLD_SIZE/MASTER_ADDR/MASTER_PORT), then OMPI env
    discovery (reference's ``mpi_discovery:595``).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    env = os.environ
    coordinator_address = coordinator_address or env.get("COORDINATOR_ADDRESS")
    if coordinator_address is None and env.get("MASTER_ADDR"):
        coordinator_address = f"{env['MASTER_ADDR']}:{env.get('MASTER_PORT', '29500')}"
    num_processes = num_processes or _int_env("NUM_PROCESSES") or _int_env("WORLD_SIZE")
    process_id = process_id if process_id is not None else (
        _int_env("PROCESS_ID") if "PROCESS_ID" in env else _int_env("RANK"))
    if num_processes is None and auto_mpi_discovery and "OMPI_COMM_WORLD_SIZE" in env:
        num_processes = _int_env("OMPI_COMM_WORLD_SIZE")
        process_id = _int_env("OMPI_COMM_WORLD_RANK")
        logger.info("discovered MPI environment for rendezvous")
    if num_processes is None and auto_mpi_discovery and "PMI_SIZE" in env:
        # MPICH / MVAPICH process managers (reference: mpi_discovery comm.py:595)
        num_processes = _int_env("PMI_SIZE")
        process_id = _int_env("PMI_RANK")
        logger.info("discovered PMI (MPICH) environment for rendezvous")
    if num_processes is None and auto_mpi_discovery and \
            "SLURM_NTASKS" in env:
        num_processes = _int_env("SLURM_NTASKS")
        process_id = _int_env("SLURM_PROCID")
        logger.info("discovered SLURM environment for rendezvous")
    if num_processes and num_processes > 1:
        t0 = time.perf_counter()
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        comms_logger.record_host("init_distributed", (time.perf_counter() - t0) * 1e3)
    _INITIALIZED = True


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None and v != "" else None


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size() -> int:
    """Number of processes (ranks). NOTE: under JAX one process drives many
    chips, so rank != chip; use get_device_count() for chips (the reference's
    rank==GPU identity does not hold on TPU)."""
    return jax.process_count()


def get_rank() -> int:
    return jax.process_index()


def get_local_rank() -> int:
    return 0  # one process drives all local devices under JAX


def get_device_count() -> int:
    return jax.device_count()


def get_local_device_count() -> int:
    return jax.local_device_count()


def barrier() -> None:
    """Host-level barrier: round-trip a tiny psum across all devices."""
    t0 = time.perf_counter()
    n = jax.device_count()
    if n > 1:
        from jax.sharding import Mesh, PartitionSpec as P
        import numpy as np
        from .schedule import shard_map_compat  # local: avoid import cycle
        mesh = Mesh(np.asarray(jax.devices()), ("all",))
        f = jax.jit(shard_map_compat(lambda x: lax.psum(x, "all"), mesh,
                                     in_specs=P("all"), out_specs=P(),
                                     manual_axes=("all",)))
        jax.block_until_ready(f(jnp.zeros((n,), jnp.int32)))
    else:
        jax.effects_barrier()
    comms_logger.record_host("barrier", (time.perf_counter() - t0) * 1e3)


# --------------------------------------------------------------------------
# Collectives — named-axis, for use inside jit/shard_map
# (reference: comm/comm.py all_reduce:448, all_gather:225, reduce_scatter:435,
#  all_to_all_single:328, send/recv:347,353 -> ppermute)
# --------------------------------------------------------------------------

def psum(x, axis: Axis):
    comms_logger.record("all_reduce", axis, _nbytes(x))
    return lax.psum(x, axis)


all_reduce = psum


def pmean(x, axis: Axis):
    comms_logger.record("all_reduce", axis, _nbytes(x))
    return lax.pmean(x, axis)


def pmax(x, axis: Axis):
    comms_logger.record("all_reduce_max", axis, _nbytes(x))
    return lax.pmax(x, axis)


def all_gather(x, axis: Axis, *, tiled: bool = True, gather_axis: int = 0):
    """Gather shards along `gather_axis`. tiled=True concatenates (the
    reference's all_gather_into_tensor); tiled=False stacks a new dim."""
    comms_logger.record("all_gather", axis, _nbytes(x))
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: Axis, *, scatter_axis: int = 0):
    """Sum-reduce then scatter shards (reference: reduce_scatter_fn:243 — uses
    reduce_scatter_tensor when available; XLA always has it)."""
    comms_logger.record("reduce_scatter", axis, _nbytes(x))
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def all_to_all(x, axis: Axis, *, split_axis: int, concat_axis: int):
    comms_logger.record("all_to_all", axis, _nbytes(x))
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis,
                          tiled=True)


def ppermute(x, axis: Axis, perm):
    """Point-to-point over a ring (reference's pipe p2p send/recv:
    runtime/pipe/p2p.py:49,70)."""
    comms_logger.record("ppermute", axis, _nbytes(x))
    return lax.ppermute(x, axis, perm)


def axis_index(axis: Axis):
    return lax.axis_index(axis)


def axis_size(axis: Axis):
    return lax.axis_size(axis)


def broadcast(x, axis: Axis, src_index: int = 0):
    """Broadcast the value from `src_index` along `axis` to all members.

    Reference: ``comm/comm.py`` broadcast / engine ``_broadcast_model:1019``.
    In SPMD the params are already consistent by construction; this exists for
    parity and for randomized-state sync."""
    comms_logger.record("broadcast", axis, _nbytes(x))
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == src_index, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)
