"""1-bit compressed collectives.

Reference: ``deepspeed/runtime/comm/nccl.py:53`` (NcclBackend.
compressed_allreduce — sign-compress to 1 bit/element with per-tensor scale,
allgather the packed bits + scales, decompress and reduce locally) and the
MPI twin in ``runtime/comm/mpi.py``.

TPU-native: the packing is a reshape + dot with bit weights (VPU work XLA
vectorizes), the wire op is a single `lax.all_gather` of uint8 over the
named mesh axis — 1/32nd the bytes of an f32 all-reduce ring pass. Used from
inside a `shard_map` region whose grads are per-device local (the engine's
compressed-optimizer step path).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pack_signs", "unpack_signs", "compressed_allreduce_1bit",
           "compressed_bytes"]


def pack_signs(x) -> Tuple[jnp.ndarray, int]:
    """Sign-bit pack a float tensor into uint8 (8 elements/byte).

    Returns (packed [ceil(N/8)] uint8, original element count). The sign
    convention is bit=1 for x >= 0, so exact zeros decompress to +1 — the
    reference's torch.sign maps 0 -> 0, but 0-valued momentum+error is
    measure-zero after warmup and the error feedback absorbs the difference.
    """
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % 8
    bits = (flat >= 0).astype(jnp.uint8)
    bits = jnp.pad(bits, (0, pad))
    bits = bits.reshape(-1, 8)
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8)).astype(jnp.uint8)
    packed = (bits * weights[None, :]).sum(axis=1, dtype=jnp.uint8)
    return packed, n


def unpack_signs(packed, n: int) -> jnp.ndarray:
    """Inverse of pack_signs -> f32 tensor of +-1, first n elements."""
    bits = jnp.bitwise_and(
        packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :], 1)
    signs = bits.astype(jnp.float32) * 2.0 - 1.0
    return signs.reshape(-1)[:n]


def compressed_allreduce_1bit(x, axis_name: str):
    """Mean over `axis_name` of sign(x)*scale(x), moving only packed sign
    bits + one f32 scale per participant across the wire.

    x: per-device local f32 tensor (any shape). Returns the decompressed
    average, identical on every participant (so parameters stay in sync).
    Wire volume: N/8 bytes + 4, vs 4N (x2 for ring) dense — ~16-32x less.
    """
    shape = x.shape
    scale = jnp.mean(jnp.abs(x))
    packed, n = pack_signs(x)
    from deepspeed_tpu.comm.comm import comms_logger
    comms_logger.record("all_gather_1bit", axis_name,
                        int(packed.size) + 4)
    all_packed = lax.all_gather(packed, axis_name)        # [W, ceil(N/8)]
    all_scales = lax.all_gather(scale, axis_name)         # [W]
    W = all_scales.shape[0]

    # accumulate worker-by-worker: peak memory stays O(N), not O(W*N)
    def body(w, acc):
        return acc + unpack_signs(all_packed[w], n) * all_scales[w]

    init = jnp.zeros((n,), jnp.float32)
    try:  # under strict shard_map VMA checking the carry must be marked
        init = lax.pvary(init, axis_name)  # device-varying like the operands
    except (AttributeError, NameError):
        pass
    avg = lax.fori_loop(0, W, body, init) / W
    return avg.reshape(shape)


def compressed_bytes(x) -> int:
    """Wire bytes for one participant's contribution (packed bits + scale)."""
    n = 1
    for d in x.shape:
        n *= d
    return (n + 7) // 8 + 4
