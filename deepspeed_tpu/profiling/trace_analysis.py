"""Device-time stall attribution from a ``jax.profiler`` traced step.

The profiler's TensorBoard dump contains a Chrome-trace JSON
(``*.trace.json.gz``) whose complete events carry ``args.hlo_op`` — the
instruction name of the executed HLO op. The compiled step program's text
carries ``metadata={op_name="jit(step)/.../layer/attn/dot_general"}`` per
instruction, and ``jax.named_scope`` annotations (models/transformer.py,
runtime/engine.py) land verbatim in that path. Joining the two recovers,
for every microsecond of device time, *which op kind* ran and *which model
scope* it belongs to — the measurement half the analytic flops profiler and
the static overlap audit cannot provide.

Buckets (the taxonomy every consumer — doctor CLI, bench JSON, dryrun line
— reports):

  * ``matmul``       — dot/convolution ops (and fusions rooted on one)
                       outside an attention scope
  * ``attention``    — any op under an ``attn`` named scope (flash/sparse
                       custom calls, softmax chains, QKV/O projections)
  * ``elementwise``  — everything else that computes (fusions, reduces,
                       converts, scatter/gather)
  * ``collective``   — all-reduce/all-gather/reduce-scatter/all-to-all/
                       collective-permute (sync or start/done pairs)
  * ``host-stall``   — infeed/outfeed/host transfers: device time spent
                       waiting on (or moving data to/from) the host
  * ``dispatch-gap`` — wall time inside the step span when NO device op was
                       executing: the device idled waiting for dispatch

Attribution is interval arithmetic over the event timeline, so the numbers
are wall-true: ``device_busy_ms`` is the union of op intervals (parallel
executor threads don't double-count), ``dispatch_gap_ms`` is span minus
busy, and ``exposed_comm_ms`` is collective time NOT covered by concurrent
compute — the measured counterpart of the static OverlapAudit's modeled
``telemetry/exposed_comm_ms``.
"""

import dataclasses
import gzip
import json
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

BUCKETS = ("matmul", "attention", "elementwise", "collective",
           "host-stall", "dispatch-gap")

# kinds whose events join against the graft-lint collective census
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute", "collective-broadcast")

_HOST_OPS = ("infeed", "outfeed", "copy-start", "copy-done", "send", "recv",
             "host")

# trace-event names that are profiler/executor bookkeeping, not device work
_NOISE = ("ThreadpoolListener", "ThunkExecutor", "TfrtCpu", "ParseArguments",
          "PjitFunction", "start_trace", "stop_trace", "BufferFromHost",
          "ExecuteHelper", "Await", "thunk.")


def load_trace(path: str) -> Dict[str, Any]:
    """Load a Chrome-trace JSON (optionally gzipped) into a dict."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


# --------------------------------------------------------------------------
# HLO metadata join
# --------------------------------------------------------------------------

_HLO_META_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=.*?op_name=\"([^\"]+)\"", re.M)


def parse_hlo_scopes(hlo_text: str) -> Dict[str, str]:
    """{instruction name -> op_name metadata path} over a compiled module.

    Instruction names in the trace events match the module text modulo
    executor-added suffixes (``.clone``, ``.remat``) which the join strips.
    """
    return {m.group(1): m.group(2) for m in _HLO_META_RE.finditer(hlo_text)}


_WRAPPER_RE = re.compile(r"^(?:transpose|jvp|vmap|remat|checkpoint)\((.*)\)$")


def _unwrap(seg: str) -> str:
    """transpose(jvp(layers)) -> layers: autodiff wrappers embed the user
    scope they transformed — keep it, the wrapper itself is the fwd/bwd
    marker, not the location."""
    while True:
        m = _WRAPPER_RE.match(seg)
        if not m:
            return seg
        seg = m.group(1)


def normalize_scope(op_name: str) -> Tuple[Tuple[str, ...], bool]:
    """op_name metadata -> (scope path without jit()/transpose wrappers,
    is_backward). Backward ops carry ``transpose(jvp(...))`` in the path."""
    is_bwd = "transpose(" in op_name
    parts = []
    for seg in op_name.split("/"):
        seg = _unwrap(seg)
        if not seg or seg.startswith("jit(") \
                or seg.startswith("rematted_computation") \
                or seg == "checkpoint":
            continue
        parts.append(seg)
    return tuple(parts), is_bwd


def scope_root(op_name: str, depth: int = 3) -> str:
    """First `depth` user-scope segments — the per-module aggregation key
    ("grads/layers/attn", "optimizer", ...). Depth 3 keeps the model's
    attn/mlp split visible under the engine's grads phase scope. The
    trailing primitive name is dropped when deeper context exists."""
    parts, is_bwd = normalize_scope(op_name)
    if len(parts) > 1:
        parts = parts[:-1]  # drop the primitive leaf (dot_general, ...)
    key = "/".join(parts[:depth]) or "<unattributed>"
    return key + ("[bwd]" if is_bwd else "")


# --------------------------------------------------------------------------
# bucket classification
# --------------------------------------------------------------------------

def collective_kind(hlo_op: str) -> Optional[str]:
    base = hlo_op.split(".")[0].removesuffix("-start").removesuffix("-done")
    for kind in COLLECTIVE_KINDS:
        if base == kind or base == kind.replace("-", "_"):
            return kind
    return None


def bucket_of(hlo_op: str, scope: str = "") -> str:
    """Classify one device op into the attribution taxonomy."""
    base = hlo_op.split(".")[0].lower()
    if collective_kind(hlo_op):
        return "collective"
    if any(base.startswith(h) for h in _HOST_OPS):
        return "host-stall"
    if "attn" in scope or "attention" in scope or \
            "flash" in base or "attention" in base:
        return "attention"
    if base.startswith(("dot", "convolution", "conv", "cublas", "gemm",
                        "einsum")):
        return "matmul"
    if base.startswith("fusion") and ("dot" in scope or "einsum" in scope):
        return "matmul"
    return "elementwise"


# --------------------------------------------------------------------------
# interval arithmetic
# --------------------------------------------------------------------------

def merge_intervals(ivs: Iterable[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for s, e in sorted(ivs):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def interval_total(ivs: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in ivs)


def subtract_intervals(a: List[Tuple[float, float]],
                       b: List[Tuple[float, float]]
                       ) -> List[Tuple[float, float]]:
    """Portions of (merged) `a` not covered by (merged) `b`."""
    out: List[Tuple[float, float]] = []
    for s, e in a:
        cur = s
        for bs, be in b:
            if be <= cur:
                continue
            if bs >= e:
                break
            if bs > cur:
                out.append((cur, min(bs, e)))
            cur = max(cur, be)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


# --------------------------------------------------------------------------
# attribution
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Attribution:
    """Machine-readable diagnosis of one traced step (all times ms,
    normalized per step when the capture spanned several)."""
    step_span_ms: float = 0.0          # first-to-last device event wall span
    device_busy_ms: float = 0.0        # union of device op intervals
    buckets: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)          # bucket -> {ms, count, fraction}
    by_scope_ms: Dict[str, float] = dataclasses.field(default_factory=dict)
    fwd_ms: float = 0.0
    bwd_ms: float = 0.0
    exposed_comm_ms: float = 0.0       # collective time NOT under compute
    collectives: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)          # per-kind join vs the static census
    steps: int = 1
    joined_ops: int = 0                # events matched to HLO metadata
    total_ops: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def device_events(trace: Any) -> List[Dict[str, Any]]:
    """Select the executed-HLO complete events out of a raw trace.

    Device rows are identified by ``args.hlo_op`` (CPU + TPU emit it) or, on
    TPU dumps, by a ``/device:`` process whose thread runs XLA ops. Host
    Python/runtime rows and profiler bookkeeping are dropped.
    """
    if isinstance(trace, dict):
        events = trace.get("traceEvents", [])
    else:
        events = list(trace)
    dev_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = (e.get("args") or {}).get("name", "")
            if "/device:" in pname and "CPU" not in pname:
                dev_pids.add(e.get("pid"))
    out = []
    for e in events:
        if e.get("ph") != "X" or e.get("dur", 0) <= 0:
            continue
        name = e.get("name", "")
        if any(n in name for n in _NOISE):
            continue
        args = e.get("args") or {}
        if "hlo_op" in args or (dev_pids and e.get("pid") in dev_pids):
            out.append(e)
    return out


def attribute(trace: Any, scope_map: Optional[Dict[str, str]] = None, *,
              steps: int = 1) -> Attribution:
    """Bucket a traced step's device time. ``scope_map`` (parse_hlo_scopes
    of the same compiled program) upgrades fusion/op classification with
    named-scope context; without it the op-kind heuristics still hold."""
    scope_map = scope_map or {}
    evs = device_events(trace)
    attr = Attribution(steps=max(1, int(steps)))
    attr.total_ops = len(evs)
    if not evs:
        return attr
    k = attr.steps
    bucket_ms: Dict[str, float] = {}
    bucket_n: Dict[str, int] = {}
    comm_by_kind: Dict[str, Dict[str, float]] = {}
    all_ivs: List[Tuple[float, float]] = []
    compute_ivs: List[Tuple[float, float]] = []
    comm_ivs: List[Tuple[float, float]] = []
    for e in evs:
        hlo_op = (e.get("args") or {}).get("hlo_op") or e.get("name", "")
        base = hlo_op.removesuffix(".clone").removesuffix(".remat")
        scope = scope_map.get(base) or scope_map.get(hlo_op) or ""
        if scope:
            attr.joined_ops += 1
        b = bucket_of(hlo_op, scope)
        dur_ms = e["dur"] / 1e3
        ts, te = e["ts"], e["ts"] + e["dur"]
        bucket_ms[b] = bucket_ms.get(b, 0.0) + dur_ms
        bucket_n[b] = bucket_n.get(b, 0) + 1
        all_ivs.append((ts, te))
        if b == "collective":
            comm_ivs.append((ts, te))
            kind = collective_kind(hlo_op) or "collective"
            kk = comm_by_kind.setdefault(kind, {"ms": 0.0, "count": 0})
            kk["ms"] += dur_ms
            kk["count"] += 1
        elif b != "host-stall":
            compute_ivs.append((ts, te))
        if scope:
            key = scope_root(scope)
            attr.by_scope_ms[key] = attr.by_scope_ms.get(key, 0.0) + dur_ms
            if normalize_scope(scope)[1]:
                attr.bwd_ms += dur_ms
            else:
                attr.fwd_ms += dur_ms
    merged = merge_intervals(all_ivs)
    span_ms = (merged[-1][1] - merged[0][0]) / 1e3
    busy_ms = interval_total(merged) / 1e3
    attr.step_span_ms = span_ms / k
    attr.device_busy_ms = busy_ms / k
    gap_ms = max(0.0, span_ms - busy_ms)
    bucket_ms["dispatch-gap"] = gap_ms
    bucket_n["dispatch-gap"] = max(0, len(merged) - 1)
    exposed = subtract_intervals(merge_intervals(comm_ivs),
                                 merge_intervals(compute_ivs))
    attr.exposed_comm_ms = interval_total(exposed) / 1e3 / k
    attr.fwd_ms /= k
    attr.bwd_ms /= k
    denom = max(span_ms, 1e-9)
    for b in sorted(bucket_ms, key=lambda b_: -bucket_ms[b_]):
        attr.buckets[b] = {
            "ms": round(bucket_ms[b] / k, 4),
            "count": bucket_n.get(b, 0),
            "fraction": round(bucket_ms[b] / denom, 4),
        }
    attr.collectives = [
        {"kind": kind, "ms": round(v["ms"] / k, 4), "count": int(v["count"])}
        for kind, v in sorted(comm_by_kind.items(), key=lambda kv: -kv[1]["ms"])]
    return attr


def join_census(attr: Attribution,
                census: Dict[str, Dict[str, int]]) -> List[Dict[str, Any]]:
    """Join measured per-kind collective time against the graft-lint static
    census (kind -> {count, bytes}) of the same compiled step. The measured
    count covering a start/done pair as 2 events is normalized by the
    census' own count; missing kinds are reported with measured 0."""
    joined = []
    measured = {c["kind"]: c for c in attr.collectives}
    for kind in sorted(set(census) | set(measured)):
        stat = census.get(kind, {})
        m = measured.get(kind, {"ms": 0.0, "count": 0})
        joined.append({
            "kind": kind,
            "measured_ms": round(float(m["ms"]), 4),
            "measured_count": int(m["count"]),
            "census_count": int(stat.get("count", 0)),
            "census_bytes": int(stat.get("bytes", 0)),
        })
    return joined


# --------------------------------------------------------------------------
# roofline classification + stall ranking
# --------------------------------------------------------------------------

def classify_bounds(attr: Attribution, cost: Optional[Dict[str, Any]] = None,
                    *, peak_flops: float = 0.0,
                    hbm_bytes_per_sec: float = 0.0) -> Dict[str, str]:
    """Per-bucket compute-bound / memory-bound / exposed-comm / host / idle
    verdicts. The compute buckets use the whole-program roofline (XLA
    cost_analysis flops + bytes vs chip peak and HBM bandwidth): achieved
    intensity below the machine balance point means the bucket's time is
    bandwidth, not MXU. Collectives are exposed-comm when their measured
    exposed time is a material fraction of their total, idle otherwise
    (fully hidden wire is not a stall)."""
    out: Dict[str, str] = {}
    intensity = None
    balance = None
    if cost and cost.get("flops_per_step") and cost.get(
            "bytes_accessed_per_step"):
        intensity = cost["flops_per_step"] / max(
            1, cost["bytes_accessed_per_step"])
    if peak_flops > 0 and hbm_bytes_per_sec > 0:
        balance = peak_flops / hbm_bytes_per_sec
    for b in attr.buckets:
        if b in ("matmul", "attention"):
            if intensity is not None and balance is not None:
                out[b] = ("compute-bound" if intensity >= balance
                          else "memory-bound")
            else:
                out[b] = "compute-bound"
        elif b == "elementwise":
            out[b] = "memory-bound"
        elif b == "collective":
            total = attr.buckets[b]["ms"]
            out[b] = ("exposed-comm"
                      if total > 0 and attr.exposed_comm_ms > 0.25 * total
                      else "overlapped-comm")
        elif b == "host-stall":
            out[b] = "host-bound"
        else:
            out[b] = "idle"
    return out


# buckets that are pure execution-efficiency (the MXU doing its job) and so
# never *stall* attribution candidates; every other bucket's time is the
# step not computing at peak
_NON_STALL = {"compute-bound", "overlapped-comm"}


def stall_ranking(attr: Attribution, bounds: Optional[Dict[str, str]] = None
                  ) -> List[Dict[str, Any]]:
    """Buckets ranked by stall time: everything whose roofline verdict is
    not compute-bound (memory-bound compute still counts — it is the thing
    a fused kernel would fix), with the collective bucket priced at its
    MEASURED exposed time only."""
    bounds = bounds or classify_bounds(attr)
    rows = []
    for b, stat in attr.buckets.items():
        verdict = bounds.get(b, "")
        if verdict in _NON_STALL:
            continue
        ms = stat["ms"]
        if b == "collective":
            ms = attr.exposed_comm_ms
            if ms <= 0:
                continue
        if ms <= 0:
            continue
        rows.append({
            "bucket": b,
            "ms": round(ms, 4),
            "fraction": round(ms / max(attr.step_span_ms, 1e-9), 4),
            "bound": verdict,
        })
    rows.sort(key=lambda r: -r["ms"])
    return rows


def stall_top2(attr: Attribution, bounds: Optional[Dict[str, str]] = None
               ) -> List[Dict[str, Any]]:
    return stall_ranking(attr, bounds)[:2]
