from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler, get_model_profile, profile_jaxpr)
from deepspeed_tpu.profiling.capture import (CaptureResult,
                                             capture_traced_step,
                                             rotate_artifacts, trace_window)
from deepspeed_tpu.profiling.trace_analysis import (Attribution, attribute,
                                                    parse_hlo_scopes,
                                                    stall_top2)
from deepspeed_tpu.profiling.doctor import diagnose, gate, stall_fields
