from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler, get_model_profile, profile_jaxpr)
