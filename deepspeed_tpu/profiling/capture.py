"""Windowed one-step ``jax.profiler`` capture -> normalized trace artifact.

The raw profiler dump is a TensorBoard run directory
(``plugins/profile/<ts>/``) containing an xplane protobuf plus a
Chrome-trace JSON. This harness drives a capture window around N engine
steps, locates the trace JSON, pairs it with the compiled step program's
text (the scope/census join input), and writes ONE self-contained gzipped
artifact next to the bench results — with rotation so repeated bench runs
can't grow the directory unbounded.

The capture perturbs nothing: profiling is observation-only (the
numerics-parity test in tests/unit/test_trace_analysis.py pins train
bits with capture on vs off), and the window is placed AFTER a warmup
step so compilation never pollutes the timeline.
"""

import contextlib
import dataclasses
import glob
import gzip
import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.profiling import trace_analysis
from deepspeed_tpu.utils.logging import logger

# artifact rotation defaults: a one-step trace of the bench model is a few
# hundred KiB gzipped; 16 artifacts / 256 MiB is ample headroom while still
# bounding a long-lived bench dir
MAX_ARTIFACTS = 16
MAX_TOTAL_BYTES = 256 << 20


@dataclasses.dataclass
class CaptureResult:
    """One captured window, ready for attribution."""
    trace: Dict[str, Any]              # Chrome-trace dict (device rows kept)
    artifact_path: str = ""            # normalized .json.gz in the out dir
    hlo_text: str = ""                 # compiled step program (scope join)
    cost: Optional[Dict[str, Any]] = None   # static_step_cost of the step
    steps: int = 1
    wall_s: float = 0.0

    def attribution(self) -> trace_analysis.Attribution:
        scope_map = (trace_analysis.parse_hlo_scopes(self.hlo_text)
                     if self.hlo_text else None)
        return trace_analysis.attribute(self.trace, scope_map,
                                        steps=self.steps)


@contextlib.contextmanager
def trace_window(log_dir: str):
    """Start/stop a jax.profiler capture; yields the log dir. Failures to
    START disable the capture (yielding None) rather than the caller."""
    import jax
    started = False
    try:
        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # noqa: BLE001 - capture must never kill a run
        logger.warning(f"capture: profiler failed to start ({e!r})")
    try:
        yield log_dir if started else None
    finally:
        if started:
            jax.profiler.stop_trace()


def find_trace_json(log_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under the profiler run directory."""
    pats = sorted(glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*", "*.trace.json.gz")),
        key=os.path.getmtime)
    # the perfetto variant duplicates the same events; prefer the plain one
    plain = [p for p in pats if not p.endswith("perfetto_trace.json.gz")]
    return (plain or pats)[-1] if pats else None


def capture_traced_step(engine, batch, out_dir: str, *, tag: str = "step",
                        steps: int = 1, keep_raw: bool = False
                        ) -> Optional[CaptureResult]:
    """Capture `steps` engine steps under the profiler and write the
    normalized artifact ``{out_dir}/trace_{tag}.json.gz``.

    The engine must be on the plain jitted path (the layer-streamed
    infinity executor compiles per-layer programs and has no single step
    to join against). Returns None when the platform yields no usable
    trace — callers degrade, they don't fail.
    """
    import jax
    import numpy as np

    def sync():
        jax.block_until_ready(engine.state)
        # through relays where block_until_ready is advisory, a host fetch
        # forces the dependency chain (same convention as bench.py)
        np.asarray(jax.device_get(jax.tree.leaves(engine.state)[0]))

    engine.train_batch(batch)    # warmup: compile outside the window
    sync()
    raw_dir = tempfile.mkdtemp(prefix="dstpu-trace-")
    try:
        t0 = time.perf_counter()
        with trace_window(raw_dir) as ld:
            if ld is None:
                return None
            for _ in range(steps):
                engine.train_batch(batch)
            sync()
        wall = time.perf_counter() - t0
        path = find_trace_json(raw_dir)
        if path is None:
            logger.warning("capture: profiler produced no trace.json.gz "
                           "(platform without host-trace export)")
            return None
        trace = trace_analysis.load_trace(path)
    finally:
        if not keep_raw:
            shutil.rmtree(raw_dir, ignore_errors=True)
    hlo_text, cost = step_program_text(engine, batch)
    res = CaptureResult(trace=trace, hlo_text=hlo_text, cost=cost,
                        steps=steps, wall_s=wall)
    if out_dir:
        res.artifact_path = write_artifact(res, out_dir, tag)
    return res


def step_program_text(engine, batch) -> tuple:
    """(compiled HLO text, static per-step cost) of the engine's own train
    step — the same artifacts graft-lint and the telemetry join read, so
    the trace join, census join and roofline all describe ONE program.

    One AOT lower+compile on abstract shapes (no execution); the dense
    jitted path is required — host-driven executors (1-bit/NVMe/infinity)
    have no single step program to join a trace against.
    """
    try:
        import jax
        from deepspeed_tpu.analysis.hlo_parse import (collective_census,
                                                      parse_overlap)
        from deepspeed_tpu.analysis.program import abstractify
        if engine._train_step is None:
            raise ValueError("capture: engine has no dense jitted step")
        batch_abs = abstractify(engine._device_batch(batch))
        state_abs = abstractify(engine.state)
        rng_abs = jax.ShapeDtypeStruct(engine._rng.shape, engine._rng.dtype)
        with engine.mesh:
            compiled = engine._train_step.lower(
                state_abs, batch_abs, rng_abs).compile()
        text = compiled.as_text()
        census = collective_census(parse_overlap(text))
        cost: Dict[str, Any] = {
            "census": {k: dict(v) for k, v in census.items()}}
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            if ca:
                cost["flops_per_step"] = int(ca.get("flops", 0))
                cost["bytes_accessed_per_step"] = int(
                    ca.get("bytes accessed", 0))
        except Exception:  # noqa: BLE001 - cost model is backend-dependent
            pass
        cost["comm_bytes_per_step"] = sum(
            c["bytes"] for c in census.values())
        return text, cost
    except Exception as e:  # noqa: BLE001 - join degrades to op heuristics
        logger.warning(f"capture: step program text unavailable ({e!r}); "
                       "attribution falls back to op-kind heuristics")
        return "", None


def write_artifact(res: CaptureResult, out_dir: str, tag: str) -> str:
    """Write the normalized artifact (device events + meta, gzipped JSON)
    and rotate older artifacts past the size/count caps."""
    os.makedirs(out_dir, exist_ok=True)
    events = trace_analysis.device_events(res.trace)
    # metadata rows keep the artifact loadable by chrome://tracing
    meta_rows = [e for e in res.trace.get("traceEvents", [])
                 if e.get("ph") == "M"]
    payload = {
        "displayTimeUnit": "ms",
        "traceEvents": meta_rows + events,
        "metadata": {
            "tool": "deepspeed_tpu.profiling.capture",
            "steps": res.steps,
            "wall_s": round(res.wall_s, 4),
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        },
    }
    path = os.path.join(out_dir, f"trace_{tag}.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump(payload, f)
    if res.hlo_text:
        hlo_path = os.path.join(out_dir, f"trace_{tag}.hlo.txt.gz")
        with gzip.open(hlo_path, "wt") as f:
            f.write(res.hlo_text)
    rotate_artifacts(out_dir)
    return path


def rotate_artifacts(out_dir: str, max_files: int = MAX_ARTIFACTS,
                     max_total_bytes: int = MAX_TOTAL_BYTES) -> List[str]:
    """Delete the oldest capture artifacts past the count/total-size caps.

    One capture = one tag = a ``trace_<tag>.json.gz`` + ``.hlo.txt.gz``
    PAIR: rotation counts and removes whole pairs (deleting just the trace
    half would orphan an hlo file the doctor's auto-guess can never use).
    Returns the paths removed; newest captures always survive."""
    groups: Dict[str, List[str]] = {}
    for p in glob.glob(os.path.join(out_dir, "trace_*")):
        tag = os.path.basename(p).split(".", 1)[0]
        groups.setdefault(tag, []).append(p)
    ordered = sorted(groups.values(),
                     key=lambda ps: max(os.path.getmtime(p) for p in ps),
                     reverse=True)
    removed = []
    total = 0
    kept = 0
    for ps in ordered:
        sz = sum(os.path.getsize(p) for p in ps)
        if kept >= max_files or total + sz > max_total_bytes:
            for p in ps:
                try:
                    os.remove(p)
                    removed.append(p)
                except OSError:
                    pass
        else:
            kept += 1
            total += sz
    return removed
