"""Flops profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:20``
(FlopsProfiler — monkey-patches torch functionals to count MACs/params per
module, prints a model-tree profile with latency-derived utilization).

TPU-native re-design: no patching — the profile falls out of the program
representation. Two complementary sources:

1. `profile_jaxpr` walks the jaxpr (through pjit/scan/cond/remat/custom_vjp)
   and counts FLOPs per primitive analytically — dot_general/conv get exact
   MXU counts, elementwise ops count 1/element. `lax.scan` multiplies its
   body by trip count, which is exactly how the stacked-layer transformer is
   expressed, so per-layer costs come out right. Grouped by `jax.named_scope`
   / source line for the per-module table.
2. XLA's own `compiled.cost_analysis()` (post-fusion flops/bytes) for the
   whole-program ground truth the achieved-MFU number is computed against.

The two usually differ a few % (XLA rematerializes and fuses); both are
reported.
"""

import dataclasses
import time
from collections import defaultdict
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


# FLOP counters per primitive ------------------------------------------------

def _dot_general_flops(eqn) -> int:
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    batch = _prod(a.shape[i] for i in lb)
    contract = _prod(a.shape[i] for i in lc)
    m = _prod(a.shape[i] for i in range(len(a.shape)) if i not in lc and i not in lb)
    n = _prod(b.shape[i] for i in range(len(b.shape)) if i not in rc and i not in rb)
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    # 2 * output elements * kernel volume * input channels (per group)
    dn = eqn.params["dimension_numbers"]
    kernel_spatial = _prod(rhs.shape[i] for i in dn.rhs_spec[2:])
    in_ch = rhs.shape[dn.rhs_spec[1]]
    return 2 * _prod(out.shape) * kernel_spatial * in_ch


_ELEMENTWISE_COST = {
    "exp": 8, "log": 8, "tanh": 8, "logistic": 8, "erf": 8, "rsqrt": 4,
    "sqrt": 4, "div": 2, "pow": 8, "sin": 8, "cos": 8,
}

_ZERO_COST = {
    "reshape", "transpose", "broadcast_in_dim", "convert_element_type",
    "slice", "dynamic_slice", "dynamic_update_slice", "squeeze",
    "concatenate", "gather", "scatter", "pad", "rev", "iota", "copy",
    "stop_gradient", "select_n", "bitcast_convert_type", "split",
}


def _eqn_flops(eqn) -> int:
    prim = eqn.primitive.name
    if prim == "dot_general":
        return _dot_general_flops(eqn)
    if prim == "conv_general_dilated":
        return _conv_flops(eqn)
    if prim in _ZERO_COST:
        return 0
    out_elems = sum(_prod(v.aval.shape) for v in eqn.outvars
                    if hasattr(v.aval, "shape"))
    return _ELEMENTWISE_COST.get(prim, 1) * out_elems


_CALL_PRIMS = ("pjit", "closed_call", "remat", "checkpoint", "custom_vjp_call",
               "custom_jvp_call", "custom_vjp_call_jaxpr", "core_call",
               "named_call", "shard_map")


def _inner_jaxprs(eqn):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in eqn.params:
            j = eqn.params[key]
            yield j.jaxpr if hasattr(j, "jaxpr") else j
            return
    for key in ("branches",):
        if key in eqn.params:
            for j in eqn.params[key]:
                yield j.jaxpr if hasattr(j, "jaxpr") else j
            return


def profile_jaxpr(jaxpr, *, scale: int = 1,
                  by: Optional[Dict[str, int]] = None,
                  by_scope: Optional[Dict[str, int]] = None) -> Tuple[int, Dict, Dict]:
    """Walk a jaxpr, returning (total_flops, flops_by_primitive,
    flops_by_name_scope). scan bodies are multiplied by trip count; cond
    branches contribute their max."""
    by = {} if by is None else by
    by_scope = {} if by_scope is None else by_scope
    total = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "pallas_call":
            # the kernel body jaxpr describes ONE grid program; the launch
            # executes it prod(grid) times (sparse/flash attention express
            # their block loop through the grid, so counting the body once
            # reported ~zero attention FLOPs — the r6 coverage gap)
            gm = eqn.params.get("grid_mapping")
            grid = _prod(getattr(gm, "grid", ()) or (1,))
            inner = eqn.params["jaxpr"]
            inner = inner.jaxpr if hasattr(inner, "jaxpr") else inner
            t, _, _ = profile_jaxpr(inner, scale=scale * grid, by=by,
                                    by_scope=by_scope)
            total += t * grid
        elif prim == "scan":
            inner = eqn.params["jaxpr"].jaxpr
            length = int(eqn.params["length"])
            t, _, _ = profile_jaxpr(inner, scale=scale * length, by=by,
                                    by_scope=by_scope)
            total += t * length
        elif prim == "while":
            inner = eqn.params["body_jaxpr"].jaxpr
            t, _, _ = profile_jaxpr(inner, scale=scale, by=by,
                                    by_scope=by_scope)
            total += t  # trip count unknown; count one iteration
        elif prim == "cond":
            # count only the most expensive branch — and only its entries in
            # the breakdown tables, so they still sum to the total
            best = None
            for bj in eqn.params["branches"]:
                b2: Dict[str, int] = {}
                bs2: Dict[str, int] = {}
                t, _, _ = profile_jaxpr(bj.jaxpr, scale=scale, by=b2,
                                        by_scope=bs2)
                if best is None or t > best[0]:
                    best = (t, b2, bs2)
            if best is not None:
                total += best[0]
                for k, v in best[1].items():
                    by[k] = by.get(k, 0) + v
                for k, v in best[2].items():
                    by_scope[k] = by_scope.get(k, 0) + v
        elif any(k in eqn.params for k in ("jaxpr", "call_jaxpr", "fun_jaxpr")):
            for inner in _inner_jaxprs(eqn):
                t, _, _ = profile_jaxpr(inner, scale=scale, by=by,
                                        by_scope=by_scope)
                total += t
        else:
            f = _eqn_flops(eqn)
            if f:
                total += f
                by[prim] = by.get(prim, 0) + f * scale
                scope = _eqn_scope(eqn)
                by_scope[scope] = by_scope.get(scope, 0) + f * scale
    return total, by, by_scope


def _eqn_scope(eqn) -> str:
    st = eqn.source_info.name_stack
    s = str(st) if st is not None else ""
    if s:
        # keep two scope levels ("layers/attn") — the same aggregation key
        # trace_analysis.scope_root uses, so the measured join lines up
        return "/".join(s.split("/")[:2])
    tb = eqn.source_info.traceback
    if tb is not None:
        frames = tb.frames if hasattr(tb, "frames") else []
        for fr in frames:
            fn = getattr(fr, "file_name", "")
            if "deepspeed_tpu" in fn or "site-packages" not in fn:
                return f"{fn.rsplit('/', 1)[-1]}:{fr.line_num}"
    return "<unattributed>"


# ---------------------------------------------------------------------------

def get_model_profile(fn: Callable, *args, backend_analysis: bool = True,
                      **kwargs) -> Dict[str, Any]:
    """Profile a jittable callable: analytic FLOPs (jaxpr walk), parameter
    count of the first arg (if a pytree of arrays), and — when a backend is
    available — XLA's post-fusion cost analysis."""
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    total, by_prim, by_scope = profile_jaxpr(closed.jaxpr)
    n_params = 0
    try:
        n_params = sum(_prod(l.shape) for l in jax.tree.leaves(args[0]))
    except Exception:
        pass
    out = {"flops": total, "params": n_params,
           "flops_by_primitive": dict(sorted(by_prim.items(),
                                             key=lambda kv: -kv[1])),
           "flops_by_module": dict(sorted(by_scope.items(),
                                          key=lambda kv: -kv[1]))}
    if backend_analysis:
        try:
            compiled = jax.jit(lambda *a: fn(*a, **kwargs)).lower(*args).compile()
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            if ca:
                out["xla_flops"] = int(ca.get("flops", 0))
                out["xla_bytes_accessed"] = int(ca.get("bytes accessed", 0))
            ma = compiled.memory_analysis()
            if ma is not None and hasattr(ma, "temp_size_in_bytes"):
                out["peak_temp_bytes"] = int(ma.temp_size_in_bytes)
        except Exception as e:  # pragma: no cover - backend-specific
            logger.debug(f"backend cost analysis unavailable: {e!r}")
    return out


def measured_module_profile(engine, batch, *, steps: int = 1,
                            out_dir: str = "") -> Optional[Dict[str, Any]]:
    """Measured per-module latency + achieved FLOPS from a real traced step.

    The analytic tables above say what the program SHOULD cost; this runs
    the engine's own jitted step under ``jax.profiler`` (profiling/capture)
    and joins the trace's per-named-scope device time with the analytic
    per-scope FLOPs — the reference flops profiler's latency column, fed by
    a hardware trace instead of host-side module timers. Returns None when
    the platform yields no trace (callers degrade)."""
    from deepspeed_tpu.profiling.capture import capture_traced_step
    res = capture_traced_step(engine, batch, out_dir, tag="flops",
                              steps=steps)
    if res is None:
        return None
    attr = res.attribution()
    # analytic per-scope fwd flops of the same model (loss_fn jaxpr walk)
    flops_by_scope: Dict[str, int] = {}
    try:
        state, rng = engine.state, jax.random.PRNGKey(0)
        closed = jax.make_jaxpr(
            lambda p, bt, r: engine.model.loss_fn(p, bt, r, False))(
            state["params"], batch, rng)
        _, _, flops_by_scope = profile_jaxpr(closed.jaxpr)
    except Exception as e:  # noqa: BLE001 - join degrades to latency-only
        logger.debug(f"measured profile: analytic join unavailable: {e!r}")
    modules = []
    for scope, ms in sorted(attr.by_scope_ms.items(), key=lambda kv: -kv[1]):
        # measured keys carry engine phases + bwd markers the analytic
        # (forward-only) table doesn't: grads/layers[bwd] -> layers
        is_bwd = scope.endswith("[bwd]")
        bare = scope.removesuffix("[bwd]")
        for prefix in ("grads/", "optimizer/"):
            bare = bare.removeprefix(prefix)
        row: Dict[str, Any] = {"module": scope,
                               "measured_ms": round(ms, 3)}
        fl = flops_by_scope.get(bare) or flops_by_scope.get(
            bare.split("/")[0])
        if fl and ms > 0 and not is_bwd:
            # fwd rows only: the analytic walk covers the forward pass, so
            # dividing it by backward device time would understate bwd
            # throughput ~2-3x and mislead exactly the table meant to
            # guide perf work
            row["analytic_fwd_flops"] = int(fl)
            row["achieved_tflops"] = round(fl / (ms / 1e3) / 1e12, 4)
        modules.append(row)
    return {"modules": modules,
            "buckets": attr.buckets,
            "step_span_ms": round(attr.step_span_ms, 4),
            "device_busy_ms": round(attr.device_busy_ms, 4),
            "fwd_ms": round(attr.fwd_ms, 4),
            "bwd_ms": round(attr.bwd_ms, 4),
            "trace_artifact": res.artifact_path}


def _fmt_flops(f: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(f) < 1000:
            return f"{f:.2f} {unit}FLOPs"
        f /= 1000
    return f"{f:.2f} EFLOPs"


class FlopsProfiler:
    """Engine-attached profiler (reference: ``flops_profiler/profiler.py:20``
    FlopsProfiler + its get_model_profile API).

    The engine calls `profile_step(engine, batch)` once at the configured
    step: it profiles the jitted train step, measures wall clock over a few
    steps, and prints the reference-style report (total params, fwd+bwd
    flops, per-module and per-primitive breakdown, achieved TFLOPS/MFU).
    """

    def __init__(self, config):
        self.cfg = config
        self.profile: Optional[Dict[str, Any]] = None

    def run(self, engine, batch, measure_steps: int = 3) -> Dict[str, Any]:
        from deepspeed_tpu.accelerator import get_accelerator
        state, rng = engine.state, jax.random.PRNGKey(0)

        def step_fn(state, batch, rng):
            return engine.model.loss_fn(state["params"], batch, rng, False)

        prof = get_model_profile(step_fn, state, batch, rng)
        prof["params"] = sum(_prod(l.shape) for l in
                             jax.tree.leaves(state["params"]))
        # forward flops from the loss; train step ~ 3x (fwd + bwd re-fwd)
        prof["train_flops_estimate"] = 3 * prof["flops"]

        # time real steps WITHOUT perturbing the training trajectory: run
        # them on a copy of the state (2x state memory for the duration;
        # NVMe-swapped optimizer state is the one residue this can't shield)
        saved_state = engine.state
        saved = (engine.global_steps, engine.micro_steps,
                 getattr(engine, "_onebit_applied", None), engine._rng)
        engine.state = jax.tree.map(jnp.copy, saved_state)
        try:
            t0 = time.perf_counter()
            for _ in range(measure_steps):
                engine.train_batch(batch)
            dt = (time.perf_counter() - t0) / measure_steps
        finally:
            engine.state = saved_state
            engine.global_steps, engine.micro_steps = saved[0], saved[1]
            if saved[2] is not None:
                engine._onebit_applied = saved[2]
            engine._rng = saved[3]  # keep the dropout stream bit-identical
        prof["step_latency_s"] = dt
        accel = get_accelerator()
        peak = accel.peak_flops_per_device("bf16") * max(1, jax.device_count())
        prof["achieved_tflops"] = prof["train_flops_estimate"] / dt / 1e12
        prof["mfu"] = prof["train_flops_estimate"] / dt / peak
        if getattr(self.cfg, "measure_trace", False):
            try:
                prof["measured"] = measured_module_profile(
                    engine, batch, out_dir=self.cfg.trace_dir)
            except Exception as e:  # noqa: BLE001 - measured tier degrades
                logger.warning(f"flops profiler: measured trace tier "
                               f"failed: {e!r}")
        self.profile = prof
        report = self.format_report(prof)
        if self.cfg.output_file:
            with open(self.cfg.output_file, "w") as f:
                f.write(report)
        logger.info("\n" + report)
        return prof

    def format_report(self, prof: Dict[str, Any]) -> str:
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler "
            "--------------------------",
            f"params:                {prof['params'] / 1e6:.2f} M",
            f"fwd flops (analytic):  {_fmt_flops(prof['flops'])}",
            f"train flops (~3x fwd): {_fmt_flops(prof['train_flops_estimate'])}",
        ]
        if "xla_flops" in prof:
            lines.append(f"fwd flops (XLA):       {_fmt_flops(prof['xla_flops'])}")
        if "step_latency_s" in prof:
            lines += [
                f"step latency:          {prof['step_latency_s'] * 1e3:.2f} ms",
                f"achieved:              {prof['achieved_tflops']:.2f} TFLOPS "
                f"(MFU {prof['mfu'] * 100:.1f}%)",
            ]
        top = self.cfg.top_modules if self.cfg.top_modules > 0 else 5
        if self.cfg.detailed and prof.get("flops_by_module"):
            lines.append("per-module (name-scope/source) fwd flops:")
            for k, v in list(prof["flops_by_module"].items())[:max(top, 5)]:
                lines.append(f"  {k:<40} {_fmt_flops(v)}")
        if self.cfg.detailed and prof.get("flops_by_primitive"):
            lines.append("per-primitive fwd flops:")
            for k, v in list(prof["flops_by_primitive"].items())[:8]:
                lines.append(f"  {k:<40} {_fmt_flops(v)}")
        measured = prof.get("measured")
        if measured:
            lines.append(f"measured (traced step, "
                         f"{measured['step_span_ms']:.2f} ms span, device "
                         f"busy {measured['device_busy_ms']:.2f} ms):")
            for row in measured["modules"][:10]:
                extra = (f"  {row['achieved_tflops']:.3f} TFLOPS"
                         if "achieved_tflops" in row else "")
                lines.append(f"  {row['module']:<36} "
                             f"{row['measured_ms']:>9.3f} ms{extra}")
        lines.append("-" * 84)
        return "\n".join(lines)
